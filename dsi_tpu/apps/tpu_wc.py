"""tpu_wc: word count with an on-device map-side combiner.

This is the plugin BASELINE.json's north star calls ``mrapps/tpuwc.go``: the
same job as ``wc`` (reference ``mrapps/wc.go:21-44``) but the map task's
tokenize/bucket hot loop (``mr/worker.go:69-78``) runs as the fused TPU
kernel in ``dsi_tpu/ops/wordcount.py`` via the ``--backend=tpu`` worker flag.

Map emits one record per *unique* word per split, valued with its in-split
count (a combiner), so Reduce sums counts instead of counting occurrences.
The merged ``mr-out-*`` output is byte-identical to ``wc``'s — only the
intermediate record multiplicity differs, which the differential harness
deliberately ignores (it compares final output, test-mr.sh:52-53).

The host ``Map`` below is the exact fallback the TPU runner uses for
non-ASCII splits, so correctness never depends on the kernel.
"""

from __future__ import annotations

from collections import Counter
from typing import List, Optional

from dsi_tpu.apps.wc import tokenize
from dsi_tpu.mr.types import KeyValue

#: The C++ job kernels (native/wcjob.cpp via backends/native.py) implement
#: exactly this app's combiner semantics — Map emits per-unique counts,
#: Reduce sums them.
native_kind = "wc_combine"


def Map(filename: str, contents: str) -> List[KeyValue]:
    counts = Counter(tokenize(contents))
    return [KeyValue(w, str(c)) for w, c in sorted(counts.items())]


def Reduce(key: str, values: List[str]) -> str:
    return str(sum(int(v) for v in values))


def split_unicode_runs(raw: bytes):
    """Partition a split for block-level Unicode fallback (VERDICT r4
    weakness #5: one stray non-ASCII byte used to forfeit the device for
    the WHOLE split).

    Returns ``None`` when the split is too non-ASCII to be worth
    splitting, else ``(clean_bytes, dirty_pieces)`` where ``clean_bytes``
    is the split with every dirty letter-run blanked to spaces (device
    counts it exactly) and ``dirty_pieces`` are the blanked runs' bytes
    (host tokenizes them; counts add).

    Exactness: a "run" is a maximal stretch of ASCII letters and/or
    bytes >= 0x80.  In UTF-8 every byte of a multi-byte code point is
    >= 0x80 and every ASCII byte is a standalone code point, so a
    Unicode-letter token can never cross an ASCII non-letter byte — runs
    are token-closed, and decoding a dirty run in isolation (same
    ``errors="replace"`` policy as the host fallback) yields exactly the
    tokens it yields in context.  Digits/underscores are non-letters in
    both views (``wc.go:23`` splits on them), so they bound runs too.
    """
    import numpy as np

    arr = np.frombuffer(raw, np.uint8)
    high = arr >= 128
    if not high.any():
        return raw, []
    letterish = (((arr >= 65) & (arr <= 90))
                 | ((arr >= 97) & (arr <= 122)) | high)
    m = letterish.astype(np.int8)
    starts = np.flatnonzero(np.diff(np.concatenate(
        (np.zeros(1, np.int8), m))) == 1)
    ends = np.flatnonzero(np.diff(np.concatenate(
        (m, np.zeros(1, np.int8)))) == -1) + 1
    ch = np.concatenate(([0], np.cumsum(high, dtype=np.int64)))
    dirty = np.flatnonzero(ch[ends] - ch[starts] > 0)
    dirty_bytes = int((ends[dirty] - starts[dirty]).sum())
    if dirty_bytes * 4 > len(raw):
        return None  # mostly non-ASCII: the whole-split host path wins
    clean = arr.copy()
    pieces = []
    for i in dirty.tolist():
        s, e = int(starts[i]), int(ends[i])
        pieces.append(raw[s:e])
        clean[s:e] = 32  # spaces: non-letter, creates no tokens
    return clean.tobytes(), pieces


def tpu_map(filename: str, raw: bytes) -> Optional[List[KeyValue]]:
    """Device map: fused tokenize/group/count; None -> host fallback.

    Non-ASCII inputs are split block-level: dirty letter-runs go to the
    host tokenizer, everything else stays on device — one stray
    smart-quote costs the affected runs, not the split."""
    from dsi_tpu.ops.wordcount import count_words_host_result

    parts = split_unicode_runs(raw)
    if parts is None:
        return None
    clean, dirty_pieces = parts
    res = count_words_host_result(clean)
    if res is None:
        return None
    counts = Counter()
    for w, (c, _) in res.items():
        counts[w] = c
    if dirty_pieces:
        counts.update(tokenize(
            b" ".join(dirty_pieces).decode("utf-8", errors="replace")))
    return [KeyValue(w, str(c)) for w, c in sorted(counts.items())]
