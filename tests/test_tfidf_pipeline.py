"""Pipelined TF-IDF wave walk (parallel/tfidf.py on the shared
dispatch/finish core, parallel/pipeline.py).

Oracle discipline as everywhere else: every (depth, device_accumulate,
forced-overflow) grid point must agree BIT-FOR-BIT with the depth=1
lockstep walk and with a host Counter over the Go tokenizer semantics —
including per-word posting-list ORDER, which is how a wave-order bug in
the window or the postings buffer's overflow recovery would surface.
"""

import collections
import re

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.parallel.shuffle import default_mesh
from dsi_tpu.parallel.tfidf import tfidf_sharded

WORDS = re.compile(r"[A-Za-z]+")


def _mesh():
    return default_mesh(8)


def _letters(i: int) -> str:
    return "".join(chr(97 + (i // 26 ** j) % 26) for j in range(3))


VOCAB = [_letters(i) for i in range(800)]


def _overflow_docs(n_docs: int = 18, seed: int = 31):
    """Docs whose early waves fit u_cap=64 and whose later waves overflow
    it (vocab >> 64 uniques per doc), with lengths arranged so the
    longest-first wave plan puts LOW-vocab docs first — the capacity
    overflow then arrives mid-walk, inside a full pipeline window."""
    rng = np.random.default_rng(seed)
    docs = []
    for i in range(n_docs):
        if i < n_docs // 2:  # long, low-vocab: scheduled first
            words = [VOCAB[j] for j in rng.integers(0, 8, 500)]
        else:  # shorter, high-vocab: overflow u_cap=64 mid-walk
            words = [VOCAB[j] for j in rng.integers(0, 400, 300)]
        docs.append((" ".join(words) + "\n").encode())
    return docs


def _df_oracle(docs):
    df = collections.Counter()
    for d in docs:
        for w in set(WORDS.findall(d.decode())):
            df[w] += 1
    return dict(df)


def test_pipeline_parity_grid_with_forced_replay():
    """depth x device_accumulate grid over a stream that forces a
    mid-walk capacity overflow: every point bit-identical to the depth=1
    lockstep path (counts, partitions, AND per-word posting order), with
    the deferred check actually replaying (counts would double on a
    commit-then-replay bug, halve on a dropped wave)."""
    docs = _overflow_docs()
    mesh = _mesh()
    base_st: dict = {}
    base = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=64, depth=1,
                         wave_stats=base_st)
    assert base is not None
    assert base_st["replays"] >= 1  # the overflow path really ran
    got_df = {w: len(pairs) for w, (_, pairs) in base.items()}
    assert got_df == _df_oracle(docs)  # exact vs the host oracle

    for depth in (2, 3):
        for dacc in (False, True):
            st: dict = {}
            res = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=64,
                                depth=depth, device_accumulate=dacc,
                                sync_every=3, wave_stats=st)
            assert res is not None
            assert res == base, (depth, dacc)
            assert st["replays"] >= 1, (depth, dacc)
            assert st["max_inflight_waves"] <= depth
            if dacc:
                assert st["step_pulls"] == 0
                assert st["appends"] >= 1


def test_pipeline_sticky_capacity_bounds_replays():
    """The widened capacity sticks: once one wave replays wider, later
    waves dispatch at the wide rung directly — replays are bounded by
    the in-flight window plus the overflow transition, not the walk."""
    docs = _overflow_docs(n_docs=24)
    st: dict = {}
    res = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=64,
                        depth=3, wave_stats=st)
    assert res is not None
    assert st["waves"] == 3  # 24 docs / 8 devices
    # One replay per wave still in flight at the transition, at most.
    assert 1 <= st["replays"] <= 3


def test_pipeline_depth_env_default(monkeypatch):
    monkeypatch.setenv("DSI_STREAM_PIPELINE_DEPTH", "3")
    docs = _overflow_docs(n_docs=8, seed=3)
    st: dict = {}
    res = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                        wave_stats=st)
    assert res is not None and st["depth"] == 3
    st = {}
    res = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                        depth=1, wave_stats=st)
    assert res is not None and st["depth"] == 1


def test_pipeline_postings_overflow_recovery_preserves_order(monkeypatch):
    """The lagged device-postings buffer under a forced-tiny capacity:
    appends no-op mid-window (sticky dirty bit), recovery drains and
    re-appends — and the result is STILL bit-identical to the lockstep
    host-pull walk, proving wave order survived the recovery."""
    monkeypatch.setenv("DSI_DEVICE_POSTINGS_CAP", "256")
    rng = np.random.default_rng(7)
    docs = [(" ".join(VOCAB[j] for j in rng.integers(0, 300, 350))
             + "\n").encode() for _ in range(24)]
    mesh = _mesh()
    base = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                         depth=1)
    st: dict = {}
    # sync_every far beyond the wave count: only overflow can drain
    # before the end-of-walk sync, so recovery MUST run.
    res = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                        depth=3, device_accumulate=True,
                        sync_every=10_000, wave_stats=st)
    assert base is not None and res is not None
    assert res == base
    assert st["append_overflows"] >= 1
    assert st["step_pulls"] == 0


def test_pipeline_wave_phases_attribution():
    """wave_phases mirrors stream_phases: the per-phase walls exist, are
    finite, and the background materializer actually ran off the main
    thread (materialize_wait_s key present at depth > 1)."""
    docs = _overflow_docs(n_docs=16, seed=11)
    st: dict = {}
    res = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                        depth=2, wave_stats=st)
    assert res is not None
    for k in ("materialize_s", "materialize_wait_s", "upload_s",
              "kernel_s", "pull_s", "merge_s", "replay_s"):
        assert k in st and st[k] >= 0.0, k
    assert st["waves"] == 2 and st["max_inflight_waves"] <= 2


def test_pipeline_partition_slices_union_unchanged():
    """The partition-slice contract survives the pipelined walk: slices
    union to the full result, each holding only its words."""
    docs = _overflow_docs(n_docs=10, seed=5)
    mesh = _mesh()
    full = tfidf_sharded(docs, mesh=mesh, n_reduce=6, u_cap=1 << 9,
                         depth=3)
    lo = tfidf_sharded(docs, mesh=mesh, n_reduce=6, u_cap=1 << 9,
                       depth=3, partitions={0, 1, 2})
    hi = tfidf_sharded(docs, mesh=mesh, n_reduce=6, u_cap=1 << 9,
                       depth=3, partitions={3, 4, 5})
    assert full is not None and lo is not None and hi is not None
    assert set(lo) | set(hi) == set(full)
    assert not set(lo) & set(hi)
    for w, (part, pairs) in lo.items():
        assert part in {0, 1, 2} and pairs == full[w][1]
