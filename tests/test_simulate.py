"""Vmapped crash-test model checker: invariants over randomized schedules."""

import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.parallel.simulate import run_crash_model_check, simulate_job


def test_no_faults_fast_and_clean():
    agg = run_crash_model_check(64, exit_prob=0.0, stall_prob=0.0,
                                horizon=200)
    assert agg["all_finished"] and agg["all_consistent"] and agg["all_safe"]
    assert agg["total_requeues"] == 0
    assert agg["total_duplicate_completions"] == 0
    assert agg["instances_where_reference_counter_breaks_barrier"] == 0


def test_crashes_recovered_invariants_hold():
    agg = run_crash_model_check(512, exit_prob=0.25, stall_prob=0.2,
                                horizon=800)
    # liveness: the 10s-requeue mechanism recovers every instance
    assert agg["all_finished"], agg
    # safety: Done => all logs COMPLETED; barrier never violated
    assert agg["all_consistent"] and agg["all_safe"], agg
    # the fault model actually exercised the requeue path
    assert agg["total_requeues"] > 0


def test_stalls_produce_duplicate_completions():
    agg = run_crash_model_check(256, exit_prob=0.0, stall_prob=0.5,
                                timeout=5, horizon=800)
    assert agg["all_finished"] and agg["all_consistent"] and agg["all_safe"]
    assert agg["total_duplicate_completions"] > 0
    # With duplicates flowing, the reference's every-RPC counters would have
    # opened the reduce barrier early in at least some schedules — the defect
    # SURVEY.md §5 documents (mr/coordinator.go:30-31,38-39).
    assert agg["instances_where_reference_counter_breaks_barrier"] > 0


def test_single_instance_deterministic():
    k = jax.random.PRNGKey(42)
    a = jax.device_get(simulate_job(k))
    b = jax.device_get(simulate_job(k))
    assert a["ticks"] == b["ticks"] and a["requeues"] == b["requeues"]
