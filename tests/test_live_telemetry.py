"""The live telemetry plane (ISSUE 10): stage latency histograms,
the stall watchdog, /statusz + /metrics, and the bench_diff gate.

Pins the histogram bucket/percentile/merge math (hypothesis property
dormant without it), the forced-stall exactly-once contract, the
endpoint smoke against a live soak subprocess, the disabled-mode
zero-thread/zero-alloc guarantee, the coordinator's percentile-aware
heartbeat classification, and bench_diff's threshold units on
synthetic pairs plus the real r04→r05 artifacts.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from dsi_tpu.obs import hist as obs_hist
from dsi_tpu.obs.hist import (HIST_SNAPSHOT_KEYS, HIST_STAGES,
                              LatencyHistogram, StageHistograms)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # dormant without hypothesis, like the fuzz suite
    HAVE_HYPOTHESIS = False


@pytest.fixture
def clean_plane():
    """Force the histogram plane off before AND after — tests must not
    inherit (or leak) a live activation."""
    obs_hist.deactivate(force=True)
    yield
    obs_hist.deactivate(force=True)


# ── histogram core ─────────────────────────────────────────────────────


def test_histogram_bucket_units():
    h = LatencyHistogram()
    # Monotonic bucketing, sub-microsecond clamps to bucket 0.
    assert h.bucket_of(0.0) == 0
    assert h.bucket_of(5e-7) == 0
    last = -1
    for us in (1, 2, 5, 10, 100, 1e3, 1e4, 1e6, 1e8):
        b = h.bucket_of(us / 1e6)
        assert b >= last, us
        last = b
    # A bucket's midpoint brackets the values that land in it.
    for v in (3.7e-6, 1.2e-3, 0.25, 7.0):
        b = h.bucket_of(v)
        mid = h.bucket_mid_s(b)
        assert mid == pytest.approx(v, rel=0.15), (v, b, mid)


def test_histogram_percentiles_and_snapshot_keys(clean_plane):
    h = LatencyHistogram()
    assert h.percentile(0.5) == 0.0  # empty: no samples, no invention
    for _ in range(99):
        h.record(0.010)
    h.record(1.0)
    assert h.count == 100
    assert h.percentile(0.50) == pytest.approx(0.010, rel=0.15)
    assert h.percentile(0.99) == pytest.approx(0.010, rel=0.15)
    assert h.percentile(1.00) == pytest.approx(1.0, rel=0.15)
    snap = h.snapshot()
    assert tuple(snap) == HIST_SNAPSHOT_KEYS
    assert snap["max_ms"] == pytest.approx(1000.0, rel=0.01)
    assert snap["count"] == 100


def test_histogram_merge_is_bucket_exact():
    a, b, both = (LatencyHistogram() for _ in range(3))
    for i, v in enumerate((1e-5, 3e-4, 0.002, 0.002, 0.7, 12.0)):
        (a if i % 2 else b).record(v)
        both.record(v)
    a.merge(b)
    assert a._counts == both._counts
    assert a.count == both.count
    assert a.total_s == pytest.approx(both.total_s)
    assert a.max_s == both.max_s


if HAVE_HYPOTHESIS:

    @settings(max_examples=60, deadline=None)
    @given(xs=st.lists(st.floats(min_value=2e-6, max_value=50.0,
                                 allow_nan=False, allow_infinity=False),
                       min_size=1, max_size=80),
           ys=st.lists(st.floats(min_value=2e-6, max_value=50.0,
                                 allow_nan=False, allow_infinity=False),
                       max_size=80))
    def test_histogram_merge_property(xs, ys):
        """merge(h(xs), h(ys)) == h(xs+ys) bucket-for-bucket, and its
        percentiles stay within bucket resolution of the true ones."""
        import math

        ha, hb, hall = (LatencyHistogram() for _ in range(3))
        for v in xs:
            ha.record(v)
            hall.record(v)
        for v in ys:
            hb.record(v)
            hall.record(v)
        ha.merge(hb)
        assert ha._counts == hall._counts
        assert ha.count == len(xs) + len(ys)
        data = sorted(xs + ys)
        for q in (0.5, 0.9, 0.99):
            true = data[max(1, math.ceil(q * len(data))) - 1]
            got = ha.percentile(q)
            assert true / 1.2 <= got <= true * 1.2, (q, true, got)

else:  # pragma: no cover

    @pytest.mark.skip(reason="hypothesis not installed (dormant)")
    def test_histogram_merge_property():
        pass


# ── span-close recording ───────────────────────────────────────────────


def test_hot_spans_record_without_tracing(clean_plane):
    """statusz-without-tracing mode: the plane active, the tracer
    disabled — hot-stage spans still record their close latency, and
    nothing lands in the trace buffer."""
    from dsi_tpu.obs.trace import _NOOP_SPAN, Tracer

    hs = obs_hist.activate()
    t = Tracer(enabled=False)
    with t.span("kernel"):
        time.sleep(0.002)
    with t.span("materialize"):  # not a hot stage: stays a no-op
        pass
    stats: dict = {}
    with t.span("upload", stats=stats, key="upload_s"):
        time.sleep(0.001)
    assert t.mark() == 0  # tracer stayed out of it
    assert hs.get("kernel").count == 1
    assert hs.get("upload").count == 1
    assert t.span("materialize") is _NOOP_SPAN
    assert stats["upload_s"] > 0


def test_disabled_mode_zero_threads_zero_alloc(clean_plane):
    """The acceptance bar's cheap half: with the plane off, hot spans
    are the shared no-op singleton, a pipeline run starts no watchdog/
    sampler threads, and the registry snapshot has no histograms."""
    from dsi_tpu.obs import get_registry
    from dsi_tpu.obs.trace import _NOOP_SPAN, Tracer
    from dsi_tpu.parallel.pipeline import StepPipeline

    t = Tracer(enabled=False)
    assert t.span("kernel") is _NOOP_SPAN
    assert obs_hist.active_histograms() is None
    stats: dict = {}
    pipe = StepPipeline(depth=1, dispatch=lambda i: i,
                        finish=lambda rec: None, stats=stats,
                        engine="offtest")
    pipe.run(lambda: iter(range(4)))
    names = {th.name for th in threading.enumerate()}
    assert not any(n.startswith(("dsi-stall-watchdog", "dsi-live-sampler",
                                 "dsi-statusz")) for n in names), names
    assert "stalls" not in stats
    assert "histograms" not in get_registry().snapshot()


# ── the stall watchdog ─────────────────────────────────────────────────


def test_forced_stall_flags_exactly_once(clean_plane, monkeypatch,
                                         capsys):
    """A sleep-injected finish past the floor produces EXACTLY ONE
    stall trace event (+ gauge + stats counter), however many watchdog
    checks elapse while it stalls."""
    from dsi_tpu.obs import get_registry, get_tracer
    from dsi_tpu.parallel.pipeline import StepPipeline

    monkeypatch.setenv("DSI_STALL_FLOOR_S", "0.2")
    monkeypatch.setenv("DSI_STALL_CHECK_S", "0.03")
    tr = get_tracer()
    was = tr.enabled
    tr.enabled = True
    mark = tr.mark()
    try:
        stats: dict = {}

        def finish(rec):
            if rec == 1:
                time.sleep(0.8)  # >> floor, spans many check intervals

        pipe = StepPipeline(depth=1, dispatch=lambda i: i, finish=finish,
                            stats=stats, engine="stalltest")
        pipe.run(lambda: iter(range(3)))
        with tr._lock:
            evs = tr._events[mark:]
    finally:
        tr.enabled = was
    stalls = [e for e in evs if e[0] == "I" and e[1] == "stall"]
    assert len(stalls) == 1, stalls
    fields = stalls[0][6]
    assert fields["engine"] == "stalltest" and fields["step"] == 1
    assert fields["age_s"] >= 0.2 and fields["threshold_s"] >= 0.2
    assert stats["stalls"] == 1
    gauge = get_registry().gauge("pipeline_stall")
    assert gauge and gauge["step"] == 1
    assert "STALL stalltest step 1" in capsys.readouterr().err


def test_deep_pipeline_window_residency_is_not_a_stall(clean_plane,
                                                       monkeypatch):
    """The watchdog thresholds on head-of-line RETIRE age, not
    dispatch→finish age: at depth 8 with steady steps, the oldest
    record's since-dispatch age is ~depth × step wall (over the floor
    here), but each head of line retires on cadence — a healthy deep
    pipeline must produce zero stall flags."""
    from dsi_tpu.obs import get_tracer
    from dsi_tpu.parallel.pipeline import StepPipeline

    monkeypatch.setenv("DSI_STALL_FLOOR_S", "0.25")
    monkeypatch.setenv("DSI_STALL_CHECK_S", "0.02")
    tr = get_tracer()
    was = tr.enabled
    tr.enabled = True
    mark = tr.mark()
    try:
        stats: dict = {}
        pipe = StepPipeline(depth=8, dispatch=lambda i: i,
                            finish=lambda rec: time.sleep(0.07),
                            stats=stats, engine="deep")
        pipe.run(lambda: iter(range(12)))  # oldest waits ~8*0.07 > floor
        with tr._lock:
            evs = tr._events[mark:]
    finally:
        tr.enabled = was
    assert not [e for e in evs if e[0] == "I" and e[1] == "stall"], \
        [e for e in evs if e[0] == "I"]
    assert "stalls" not in stats


def test_no_stall_event_for_healthy_run(clean_plane, monkeypatch):
    from dsi_tpu.obs import get_tracer
    from dsi_tpu.parallel.pipeline import StepPipeline

    monkeypatch.setenv("DSI_STALL_FLOOR_S", "5.0")
    tr = get_tracer()
    was = tr.enabled
    tr.enabled = True
    mark = tr.mark()
    try:
        stats: dict = {}
        pipe = StepPipeline(depth=2, dispatch=lambda i: i,
                            finish=lambda rec: None, stats=stats,
                            engine="healthy")
        pipe.run(lambda: iter(range(8)))
        with tr._lock:
            evs = tr._events[mark:]
    finally:
        tr.enabled = was
    assert not [e for e in evs if e[0] == "I" and e[1] == "stall"]
    assert "stalls" not in stats


# ── live sampler + endpoints ───────────────────────────────────────────


def test_live_jsonl_ring_is_bounded(clean_plane, tmp_path):
    from dsi_tpu.obs.live import LiveTelemetry

    lt = LiveTelemetry(port=0, live_dir=str(tmp_path), ring=5,
                       interval_s=60.0)
    try:
        lt.start()
        for _ in range(12):
            lt._sample_once()
        lines = (tmp_path / "live.jsonl").read_text().splitlines()
        assert len(lines) == 5  # the ring bound, not 13
        snap = json.loads(lines[-1])
        assert snap["pid"] == os.getpid() and "engines" in snap
    finally:
        lt.stop()
    # The hold is released (an explicit deactivate now works); the
    # histograms themselves survive the sampler by design.
    obs_hist.deactivate()
    assert obs_hist.active_histograms() is None


def test_statusz_and_metrics_answer_during_live_soak(tmp_path):
    """The acceptance smoke: a REAL wcstream soak subprocess serving
    --statusz-port answers /statusz with a current step ordinal and
    stage p50/p99, and /metrics with the Prometheus summary, WHILE the
    stream is running."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    errpath = tmp_path / "soak.err"
    with open(errpath, "w") as errf:
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "stream_soak.py"),
             "--mb", "8", "--chunk-bytes", "65536",
             "--statusz-port", "0", "--trace-dir", str(tmp_path)],
            stdout=subprocess.PIPE, stderr=errf, text=True, cwd=REPO,
            env=env)
    try:
        port = None
        deadline = time.time() + 60
        while time.time() < deadline and port is None:
            m = re.search(r"serving on http://127\.0\.0\.1:(\d+)/statusz",
                          errpath.read_text())
            if m:
                port = int(m.group(1))
                break
            assert proc.poll() is None, errpath.read_text()
            time.sleep(0.05)
        assert port, "statusz server never announced its port"

        def get(path):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5).read().decode()

        statusz = metrics = None
        deadline = time.time() + 180
        while time.time() < deadline and proc.poll() is None:
            try:
                txt = get("/statusz")
            except OSError:
                time.sleep(0.05)
                continue
            # Catch the engine MID-RUN: a pipeline registered and at
            # least one step dispatched.
            if re.search(r"dispatched=[1-9]", txt):
                statusz = txt
                metrics = get("/metrics")
                break
            time.sleep(0.02)
        assert statusz is not None, \
            f"never saw a live step; stderr:\n{errpath.read_text()}"
        # Current step ordinal + in-flight window, live.
        assert re.search(r"stream: dispatched=\d+ finished=\d+ "
                         r"inflight=\d+", statusz)
        assert "steps=" in statusz
        # Stage percentiles present (hot spans recorded without tracing).
        assert re.search(r"(kernel|upload|finish)\s+\d+", statusz)
        assert "p50" in statusz and "p99" in statusz
        assert "dsi_stage_latency_seconds" in metrics
        assert 'quantile="0.99"' in metrics
        assert re.search(r'dsi_pipeline_step\{engine="stream"\} \d+',
                         metrics)
        out, _ = proc.communicate(timeout=240)
        assert proc.returncode == 0, errpath.read_text()
        assert json.loads(out.strip().splitlines()[-1])["counts_exact"]
        # The bounded ring landed next to the trace artifacts.
        ring = (tmp_path / "live.jsonl").read_text().splitlines()
        assert ring and all(json.loads(l) for l in ring)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


# ── coordinator heartbeat percentiles ──────────────────────────────────


def test_requeue_is_percentile_aware(tmp_path, capsys):
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator
    from dsi_tpu.obs import get_registry

    f = tmp_path / "in.txt"
    f.write_text("alpha beta")
    cfg = JobConfig(n_reduce=2, task_timeout_s=0.25,
                    workdir=str(tmp_path))
    c = Coordinator([str(f)], 2, cfg)
    try:
        # Two contacts close together: the gap histogram learns this
        # worker phones home on a ~30 ms cadence.
        reply = c.request_task({"TaskNumber": 0, "WorkerId": "w-hist"})
        assert reply["TaskStatus"] == 0
        time.sleep(0.03)
        c.request_task({"TaskNumber": 0, "WorkerId": "w-hist"})
        hists = c.worker_heartbeat_hists()
        assert "w-hist" in hists and hists["w-hist"]["count"] >= 1
        assert tuple(hists["w-hist"]) == HIST_SNAPSHOT_KEYS
        # Never complete the task: the watchdog requeues, now with the
        # percentile classification in the record.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with c.mu:
                if c.map_log[0] == 0:
                    break
            time.sleep(0.05)
        with c.mu:
            assert c.map_log[0] == 0, "task was never requeued"
        err = capsys.readouterr().err
        assert "p99=" in err and "presumed=" in err
        # Silence (>= timeout) way past a ~30 ms p99 gap -> dead.
        assert "presumed=dead" in err
        gauge = get_registry().gauge("mr_worker_heartbeat_hist")
        assert gauge and "w-hist" in gauge
        # The armed speculative hook sees the silent worker too (give
        # the silence a beat to clear max(k*p99, timeout)).
        time.sleep(0.15)
        assert "w-hist" in c.straggler_suspects()
    finally:
        c.close()


# ── bench_diff ─────────────────────────────────────────────────────────

BENCH_DIFF = os.path.join(REPO, "scripts", "bench_diff.py")


def run_diff(*args):
    return subprocess.run([sys.executable, BENCH_DIFF, *args],
                          capture_output=True, text=True, cwd=REPO)


def _write_pair(tmp_path, old, new):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({"parsed": old}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"parsed": new}))


def test_bench_diff_flags_injected_20pct_stream_drop(tmp_path):
    _write_pair(tmp_path,
                {"value": 10.0, "stream_mbps": 10.0,
                 "stream_parity": True},
                {"value": 10.0, "stream_mbps": 8.0,
                 "stream_parity": True})
    p = run_diff("--dir", str(tmp_path))
    assert p.returncode == 1, p.stdout
    assert re.search(r"stream_mbps.*-20\.0%.*REGRESS", p.stdout)
    assert re.search(r"value.*ok", p.stdout)


def test_bench_diff_threshold_units(tmp_path):
    # Inside the 10% band: pass.  Parity flip: regress.  Lower-better:
    # overhead rising past +50% regresses, falling never does.
    _write_pair(tmp_path,
                {"stream_mbps": 10.0, "ckpt_overhead_pct": 10.0,
                 "stream_parity": True, "resume_gap_s": 0.05},
                {"stream_mbps": 9.5, "ckpt_overhead_pct": 16.0,
                 "stream_parity": False, "resume_gap_s": 0.01})
    p = run_diff("--dir", str(tmp_path))
    assert p.returncode == 1
    assert re.search(r"stream_mbps.*ok", p.stdout)
    assert re.search(r"ckpt_overhead_pct.*REGRESS", p.stdout)
    assert re.search(r"stream_parity.*true->false.*REGRESS", p.stdout.
                     replace("True->False", "true->false"))
    assert re.search(r"resume_gap_s.*ok", p.stdout)
    # An override loosens the gate.
    p2 = run_diff("--dir", str(tmp_path),
                  "--threshold", "ckpt_overhead_pct=2.0")
    assert "ckpt_overhead_pct" in p2.stdout
    assert not re.search(r"ckpt_overhead_pct.*REGRESS", p2.stdout)


def test_bench_diff_missing_keys_are_unknown_not_regress(tmp_path):
    _write_pair(tmp_path,
                {"value": 10.0, "kernel_sort_mbps": 5.0},
                {"value": 10.0, "grep_mbps": 7.0})
    p = run_diff("--dir", str(tmp_path))
    assert p.returncode == 0, p.stdout
    assert re.search(r"kernel_sort_mbps.*unknown", p.stdout)
    assert re.search(r"grep_mbps.*unknown", p.stdout)


def test_bench_diff_gates_serve_latency_row(tmp_path):
    # The ISSUE 19 tentpole number: the packed-grep arm's p99 gates
    # lower-better (a doubled tail regresses); the parity bool rides
    # the *_parity pattern; the tmux control arm stays ungated context.
    _write_pair(tmp_path,
                {"serve_pack_p99_s": 0.5, "serve_tmux_p99_s": 7.0,
                 "serve_lat_parity": True},
                {"serve_pack_p99_s": 1.6, "serve_tmux_p99_s": 20.0,
                 "serve_lat_parity": True})
    p = run_diff("--dir", str(tmp_path))
    assert p.returncode == 1, p.stdout
    assert re.search(r"serve_pack_p99_s.*REGRESS", p.stdout)
    assert not re.search(r"serve_tmux_p99_s.*REGRESS", p.stdout)
    assert re.search(r"serve_lat_parity.*ok", p.stdout)


def test_bench_diff_passes_on_real_r04_r05_pair():
    p = run_diff(os.path.join(REPO, "BENCH_r04.json"),
                 os.path.join(REPO, "BENCH_r05.json"))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "PASS" in p.stdout
