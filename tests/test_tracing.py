"""The DSI_TRACE structured-event layer (utils/tracing.py).

VERDICT r2 weakness #2 / task 6: the worker's task bodies must emit a
per-task timeline under DSI_TRACE=1, and the tracing module must carry no
dead code.  The reference has no tracing at all (SURVEY.md §5) — this layer
is additive observability; these tests pin its contract.
"""

import json

from dsi_tpu.utils.tracing import Span, log_event


def _trace_lines(capsys):
    err = capsys.readouterr().err
    out = []
    for line in err.splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("event"):
            out.append(rec)
    return out


def test_span_emits_event_when_traced(monkeypatch, capsys):
    monkeypatch.setenv("DSI_TRACE", "1")
    with Span("unit.phase", task=7) as s:
        pass
    assert s.elapsed_s >= 0
    (rec,) = _trace_lines(capsys)
    assert rec["event"] == "span"
    assert rec["name"] == "unit.phase"
    assert rec["task"] == 7
    assert rec["seconds"] >= 0


def test_silent_without_env(monkeypatch, capsys):
    monkeypatch.delenv("DSI_TRACE", raising=False)
    with Span("quiet.phase"):
        pass
    log_event("custom", x=1)
    assert _trace_lines(capsys) == []


def test_worker_tasks_emit_timeline(monkeypatch, capsys, tmp_path):
    # A real 1-coordinator + 2-worker job under DSI_TRACE=1 must produce one
    # worker.map span per input file and one worker.reduce span per
    # partition that ran.
    from tests.harness import run_distributed_threads

    monkeypatch.setenv("DSI_TRACE", "1")
    files = []
    for i in range(3):
        p = tmp_path / f"in-{i}.txt"
        p.write_text(f"alpha beta file{i} gamma")
        files.append(str(p))
    run_distributed_threads("wc", files, str(tmp_path), n_workers=2,
                            n_reduce=4)
    recs = _trace_lines(capsys)
    spans = [r for r in recs if r["event"] == "span"]
    maps = [r for r in spans if r["name"] == "worker.map"]
    reduces = [r for r in spans if r["name"] == "worker.reduce"]
    assert sorted(r["task"] for r in maps) == [0, 1, 2]
    assert {r["file"] for r in maps} == set(files)
    assert sorted(r["task"] for r in reduces) == [0, 1, 2, 3]
    assert all(r["seconds"] >= 0 for r in spans)
    # The coordinator side of the timeline: one assign and one complete per
    # task (no crashes/requeues in this run).
    assigns = [r for r in recs if r["event"] == "assign"]
    completes = [r for r in recs if r["event"] == "complete"]
    assert sorted(r["task"] for r in assigns if r["kind"] == "map") == [0, 1, 2]
    assert sorted(r["task"] for r in completes
                  if r["kind"] == "reduce") == [0, 1, 2, 3]


def test_no_dead_tracing_api():
    # PhaseTimer / maybe_jax_profile were dead code (VERDICT r2): they must
    # stay deleted rather than unreferenced.
    import dsi_tpu.utils.tracing as t

    public = {n for n in dir(t) if not n.startswith("_")
              and getattr(getattr(t, n), "__module__", None) == t.__name__}
    assert public == {"Span", "log_event"}
