"""The unified tracing/metrics subsystem (dsi_tpu/obs).

Pins the tracer core's contract — nesting, thread-safety under a
background producer, the disabled-mode zero-allocation fast path, the
durable flush discipline (atomicio CRC sidecars; survival of a REAL
``os._exit`` at a ckpt fault point) — the metrics registry's schema,
the span-totals-reconcile-with-phase-dicts acceptance criterion, and
the coordinator's requeue/heartbeat telemetry.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dsi_tpu.obs import registry as obs_registry
from dsi_tpu.obs import trace as obs_trace
from dsi_tpu.obs.registry import MetricsScope, get_registry, metrics_scope
from dsi_tpu.obs.trace import _NOOP_SPAN, Tracer
from dsi_tpu.utils.atomicio import read_bytes_verified

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ── tracer core ────────────────────────────────────────────────────────


def test_disabled_pure_span_is_shared_noop_singleton():
    t = Tracer(enabled=False)
    s1 = t.span("upload")
    s2 = t.span("kernel", step=3)
    assert s1 is _NOOP_SPAN and s2 is _NOOP_SPAN  # zero allocation
    with s1:
        pass
    assert t.mark() == 0 and t.counters == {}  # nothing buffered


def test_disabled_span_with_stats_still_accumulates():
    t = Tracer(enabled=False)
    stats = {"upload_s": 0.0}
    with t.span("upload", stats=stats, key="upload_s"):
        time.sleep(0.01)
    assert stats["upload_s"] >= 0.01
    assert t.mark() == 0  # timed for the engine, nothing traced


def test_events_and_counters_only_when_enabled():
    t = Tracer(enabled=False)
    t.event("requeue", task=1)
    t.count("steps")
    assert t.mark() == 0
    t.enabled = True
    t.event("requeue", task=1)
    t.count("steps", 2)
    assert t.counters == {"steps": 2}
    assert t.mark() == 2


def test_nesting_depth_recorded():
    t = Tracer(enabled=True)
    with t.span("finish", step=0):
        with t.span("kernel"):
            pass
        with t.span("merge"):
            pass
    rows = t.rollup()
    assert set(rows) == {"finish", "kernel", "merge"}
    # Inner spans closed first, at depth 1; the outer at depth 0.
    depths = {e[1]: e[5] for e in t._events}
    assert depths == {"kernel": 1, "merge": 1, "finish": 0}
    # Containment: children start/end inside the parent.
    by_name = {e[1]: e for e in t._events}
    f, k = by_name["finish"], by_name["kernel"]
    assert f[3] <= k[3] and k[3] + k[4] <= f[3] + f[4] + 1e-6


def test_thread_safety_under_concurrent_spans():
    t = Tracer(enabled=True)
    n_threads, per = 8, 200
    errs = []

    def work(i):
        try:
            for j in range(per):
                with t.span("materialize", step=j, thread=i):
                    pass
                t.count("items")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=work, args=(i,))
          for i in range(n_threads)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert not errs
    roll = t.rollup()
    assert roll["materialize"]["count"] == n_threads * per
    assert t.counters["items"] == n_threads * per


def test_buffer_cap_drops_are_counted_not_silent(tmp_path):
    t = Tracer(enabled=True, buffer_cap=10, trace_dir=str(tmp_path))
    for i in range(25):
        with t.span("upload", step=i):
            pass
    assert t.rollup()["upload"]["count"] == 10
    assert t.dropped == 15
    t.flush()
    meta = json.loads(
        (tmp_path / "trace.jsonl").read_text().splitlines()[0])
    assert meta["dropped_events"] == 15


def test_flush_is_durable_and_perfetto_loadable(tmp_path):
    t = Tracer(enabled=True, trace_dir=str(tmp_path))
    with t.span("upload", step=0):
        with t.span("kernel"):
            pass
    t.event("requeue", task=3, worker="w1")
    t.count("steps")
    paths = t.flush()
    assert paths is not None
    jsonl_path, json_path = paths
    # Durable-write discipline: CRC sidecars verify (atomicio).
    assert read_bytes_verified(jsonl_path) is not None
    assert read_bytes_verified(json_path) is not None
    doc = json.loads(open(json_path).read())
    evs = doc["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    assert {e["name"] for e in xs} == {"upload", "kernel"}
    for e in xs:  # the Chrome/Perfetto complete-event contract
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    # One metadata thread_name per lane, lanes distinct.
    names = {e["args"]["name"]: e["tid"] for e in evs
             if e.get("name") == "thread_name"}
    assert {"upload", "kernel", "control", "counters"} <= set(names)
    assert len(set(names.values())) == len(names)
    assert any(e.get("ph") == "i" and e["name"] == "requeue" for e in evs)
    assert any(e.get("ph") == "C" and e["name"] == "steps" for e in evs)
    # Flush is idempotent (the fault-point flush may not be the last).
    assert t.flush() is not None


def test_configure_reaps_tmp_orphans(tmp_path):
    (tmp_path / ".tmp-trace.json.x").write_text("torn")
    Tracer(enabled=True, trace_dir=str(tmp_path))
    assert not list(tmp_path.glob(".tmp-*"))


# ── metrics registry ───────────────────────────────────────────────────


def test_registry_scope_unified_and_snapshot():
    sc = metrics_scope("stream")
    assert isinstance(sc, MetricsScope) and sc.engine == "stream"
    assert get_registry().phases("stream") is sc
    sc.update({"batch_s": 1.5, "batch_wait_s": 0.25, "upload_s": 2.0,
               "max_inflight_chunks": 2, "steps": 7})
    u = sc.unified()
    assert u["materialize_s"] == 1.5
    assert u["materialize_wait_s"] == 0.25
    assert u["max_inflight"] == 2
    assert u["upload_s"] == 2.0 and u["steps"] == 7
    assert "batch_s" not in u and "max_inflight_chunks" not in u
    get_registry().set_gauge("mr_worker_heartbeat_age_s", {"w1": 0.5})
    snap = get_registry().snapshot()
    assert snap["engines"]["stream"]["materialize_s"] == 1.5
    assert snap["gauges"]["mr_worker_heartbeat_age_s"] == {"w1": 0.5}


# ── the acceptance criterion: spans reconcile with the phase dict ──────


def test_traced_stream_spans_reconcile_with_stream_phases(tmp_path,
                                                          monkeypatch):
    jax = pytest.importorskip("jax")
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import wordcount_streaming

    tracer = Tracer(enabled=True, trace_dir=str(tmp_path / "trace"))
    monkeypatch.setattr(obs_trace, "_global", tracer)
    text = ("the quick brown fox jumps over the lazy dog " * 2000).encode()
    pstats: dict = {}
    acc = wordcount_streaming(
        [text], mesh=default_mesh(8), n_reduce=10, chunk_bytes=1 << 12,
        u_cap=1 << 10, device_accumulate=True, sync_every=4,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
        pipeline_stats=pstats)
    assert acc is not None
    paths = tracer.flush()
    assert paths is not None
    roll = tracer.rollup()
    # Per-phase span totals reconcile (±5%) with the registry values the
    # same run reported — by construction they are the same measurement,
    # so this pin catches any future divergence of the two paths.
    for span_name, key in (("upload", "upload_s"), ("kernel", "kernel_s"),
                           ("materialize", "batch_s"),
                           ("fold", "fold_s"), ("sync", "sync_s"),
                           ("ckpt", "ckpt_s")):
        want = pstats[key]
        got = roll.get(span_name, {}).get("total_s", 0.0)
        assert got == pytest.approx(want, rel=0.05, abs=2e-3), \
            (span_name, key, got, want)
    # The per-step timeline exists: one finish span per step, labeled.
    assert roll["finish"]["count"] == pstats["steps"]
    doc = json.loads(open(paths[1]).read())
    fins = [e for e in doc["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "finish"]
    assert sorted(e["args"]["step"] for e in fins) == \
        list(range(pstats["steps"]))
    assert all(e["args"]["engine"] == "stream" for e in fins)
    # And the registry snapshot rode the artifact.
    meta = json.loads(
        open(paths[0]).read().splitlines()[0])
    assert meta["registry"]["engines"]["stream"]["materialize_s"] == \
        pstats["batch_s"]


# ── durable flush at a REAL crash (os._exit fault point) ───────────────


def test_trace_survives_real_process_death(tmp_path):
    corpus = tmp_path / "c.txt"
    words = " ".join(f"w{i:03d}" for i in range(120))
    corpus.write_bytes((words + "\n").encode() * 400)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "DSI_FAULT_POINT": "mid-fold", "DSI_FAULT_STEP": "3"})
    env.setdefault("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    trace_dir = tmp_path / "trace"
    p = subprocess.run(
        [sys.executable, "-m", "dsi_tpu.cli.wcstream", "--devices", "2",
         "--chunk-bytes", "8192", "--checkpoint-dir",
         str(tmp_path / "ck"), "--checkpoint-every", "1",
         "--trace-dir", str(trace_dir), "--workdir", str(tmp_path / "wd"),
         str(corpus)],
        env=env, capture_output=True, text=True, timeout=300)
    assert p.returncode == 87, p.stderr[-2000:]  # the injected crash
    # The fault-point flush committed BOTH artifacts durably before
    # os._exit: CRC-verified, parseable, and carrying the fault marker
    # plus real spans from before the crash.
    raw = read_bytes_verified(str(trace_dir / "trace.json"))
    assert raw is not None
    doc = json.loads(raw)
    names = [e["name"] for e in doc["traceEvents"]]
    assert "fault" in names and "upload" in names and "ckpt" in names
    assert read_bytes_verified(str(trace_dir / "trace.jsonl")) is not None
    # tracecat renders it without error.
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tracecat.py"),
         str(trace_dir)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-1000:]
    assert "flame" in r.stdout and "fault" in r.stdout


# ── control plane: requeue telemetry + heartbeat gauge ─────────────────


def test_requeue_logs_heartbeat_age_and_reason(tmp_path, capsys):
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator

    f = tmp_path / "in.txt"
    f.write_text("alpha beta")
    cfg = JobConfig(n_reduce=2, task_timeout_s=0.25,
                    workdir=str(tmp_path))
    c = Coordinator([str(f)], 2, cfg)
    try:
        reply = c.request_task({"TaskNumber": 0, "WorkerId": "w-test"})
        assert reply["TaskStatus"] == 0  # MAP assigned
        ages = c.worker_heartbeat_ages()
        assert "w-test" in ages and ages["w-test"] >= 0
        # Never complete it: the watchdog must requeue — loudly.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with c.mu:
                if c.map_log[0] == 0:  # LOG_UNTOUCHED again
                    break
            time.sleep(0.05)
        with c.mu:
            assert c.map_log[0] == 0, "task was never requeued"
        err = capsys.readouterr().err
        assert "requeue map task 0" in err
        assert "worker=w-test" in err and "heartbeat_age=" in err
        # The gauge was republished to the registry at requeue time.
        gauge = get_registry().gauge("mr_worker_heartbeat_age_s")
        assert gauge and "w-test" in gauge
    finally:
        c.close()
