"""Streaming SPMD path: corpus size decoupled from device/host memory.

Oracle discipline as everywhere else: exact agreement with a host Counter
over the Go tokenizer semantics, and with the one-shot sharded path.
"""

import collections
import re

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.mr.worker import ihash
from dsi_tpu.parallel.shuffle import default_mesh, wordcount_sharded
from dsi_tpu.parallel.streaming import (
    batch_stream,
    stream_files,
    wordcount_streaming,
)

WORDS = re.compile(r"[A-Za-z]+")


def _mesh():
    return default_mesh(8)


def test_batches_never_split_tokens():
    text = ("alpha beta gamma delta epsilon " * 400).encode()
    # Tiny chunks force cuts everywhere; every cut must land on a boundary.
    rebuilt = []
    for batch in batch_stream([text], n_dev=4, chunk_bytes=64):
        for row in batch:
            rebuilt.append(bytes(row).rstrip(b"\x00"))
    got = collections.Counter()
    for piece in rebuilt:
        got.update(WORDS.findall(piece.decode()))
    assert got == collections.Counter(WORDS.findall(text.decode()))


def test_streaming_matches_counter_and_partitions():
    text = ("the quick brown fox jumps over the lazy dog " * 3000).encode()
    blocks = [text[i:i + 7919] for i in range(0, len(text), 7919)]
    res = wordcount_streaming(blocks, mesh=_mesh(), n_reduce=10,
                              chunk_bytes=1 << 12, u_cap=1 << 10)
    assert res is not None
    want = collections.Counter(WORDS.findall(text.decode()))
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, p) in res.items():
        assert p == ihash(w) % 10


def test_streaming_matches_one_shot_sharded():
    rng = np.random.default_rng(7)
    words = ["tpu", "stream", "carry", "boundary", "chunk", "merge",
             "accumulate", "wave"]
    text = " ".join(words[i] for i in rng.integers(0, 8, 20_000)).encode()
    mesh = _mesh()
    stream = wordcount_streaming([text], mesh=mesh, n_reduce=10,
                                 chunk_bytes=1 << 12, u_cap=1 << 10)
    oneshot = wordcount_sharded(text, mesh=mesh, n_reduce=10, u_cap=1 << 10)
    assert stream is not None and oneshot is not None
    assert stream == oneshot


def test_streaming_non_ascii_falls_back():
    blocks = [b"plain words ", "café".encode("utf-8"), b" more words"]
    assert wordcount_streaming(blocks, mesh=_mesh(),
                               chunk_bytes=1 << 10, u_cap=1 << 8) is None


def test_streaming_giant_token_falls_back():
    # A letter run far beyond the 64-byte device word limit, positioned to
    # span a chunk cut: the streaming path must hand the job to the host.
    blocks = [b"ok words here ", b"x" * 5000, b" tail"]
    assert wordcount_streaming(blocks, mesh=_mesh(),
                               chunk_bytes=1 << 10, u_cap=1 << 8) is None


def test_stream_files_separates_documents(tmp_path):
    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_bytes(b"ends with word")
    b.write_bytes(b"word starts here")
    data = b"".join(stream_files([str(a), str(b)]))
    got = collections.Counter(WORDS.findall(data.decode()))
    # "word" twice — NOT a merged "wordword" at the file seam.
    assert got["word"] == 2 and "wordword" not in got


def test_wcstream_cli_matches_sequential_oracle(tmp_path, monkeypatch):
    """VERDICT r2 task 4: the streaming path must be reachable without
    importing internals — the wcstream CLI end-to-end vs the oracle."""
    from dsi_tpu.cli import wcstream
    from tests.harness import merged_output, oracle_output

    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=3,
                          file_size=20_000)
    want = oracle_output("wc", files, str(tmp_path))
    wd = tmp_path / "out"
    wd.mkdir()
    rc = wcstream.main(["--nreduce", "10", "--chunk-bytes", "4096",
                        "--check", "--workdir", str(wd)] + files)
    assert rc == 0  # --check exits 2 on a parity failure
    assert merged_output(str(wd)) == want


def test_wcstream_cli_host_fallback(tmp_path):
    from dsi_tpu.cli import wcstream
    from tests.harness import merged_output, oracle_output

    f = tmp_path / "in.txt"
    f.write_text("café words café and more words", encoding="utf-8")
    want = oracle_output("wc", [str(f)], str(tmp_path))
    wd = tmp_path / "out"
    wd.mkdir()
    rc = wcstream.main(["--workdir", str(wd), str(f)])
    assert rc == 0
    assert merged_output(str(wd)) == want


@pytest.mark.slow
def test_streaming_100mb_bounded_memory():
    """>=100 MB through the 8-device virtual mesh with bounded footprint:
    the corpus is a generator (never materialised), the accumulator is
    vocabulary-bounded, and every step reuses one compiled program."""
    from dsi_tpu.utils.corpus import generate_file

    base_path = "/tmp/dsi-stream-base.bin"
    generate_file(base_path, (1 << 20) - 1, seed=99)
    with open(base_path, "rb") as f:
        base = f.read() + b"\n"  # newline: no cross-repeat token merge
    repeats = 100  # ~100 MB total

    def blocks():
        for _ in range(repeats):
            yield base

    res = wordcount_streaming(blocks(), mesh=_mesh(), n_reduce=10,
                              chunk_bytes=1 << 20, u_cap=1 << 16)
    assert res is not None
    base_counts = collections.Counter(WORDS.findall(base.decode()))
    want = {w: c * repeats for w, c in base_counts.items()}
    assert {w: c for w, (c, _) in res.items()} == want


def test_streaming_aot_path_matches_counter(tmp_path, monkeypatch):
    """The aot=True bench path (AOT-cached step + full-capacity pack) on a
    single-device mesh — the exact configuration bench.py's stream row
    runs on the chip — must agree with the Counter oracle, and the warm
    pass must cover every program the stream then executes (zero compiles
    after warming)."""
    from dsi_tpu.backends import aotcache
    from dsi_tpu.parallel.streaming import warm_stream_aot

    monkeypatch.setenv("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    mesh = default_mesh(1)
    warm_stream_aot(mesh=mesh, chunk_bytes=1 << 14, caps=(1 << 10,))
    compiles_after_warm = aotcache.stats["compiles"]
    text = ("portable exact streaming " * 900).encode()
    res = wordcount_streaming([text], mesh=mesh, n_reduce=10,
                              chunk_bytes=1 << 14, u_cap=1 << 10, aot=True)
    assert res is not None
    want = collections.Counter(WORDS.findall(text.decode()))
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, part) in res.items():
        assert part == ihash(w) % 10
    assert aotcache.stats["compiles"] == compiles_after_warm


def test_stream_programs_persisted_probe_mirrors_warm(tmp_path):
    """stream_programs_persisted must hit the exact keys warm_stream_aot
    persists — a drifted mirror makes the bench silently skip its stream
    row forever on fully-warmed machines.  Single-device subprocess:
    persistence is disabled on the 8-device test mesh by design."""
    import os
    import subprocess
    import sys

    child = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from dsi_tpu.parallel.streaming import (\n"
        "    stream_programs_persisted, warm_stream_aot)\n"
        "kw = dict(chunk_bytes=1 << 14, u_cap=1 << 10)\n"
        "assert not stream_programs_persisted(**kw)\n"
        "warm_stream_aot(chunk_bytes=1 << 14, caps=(1 << 10,))\n"
        "assert stream_programs_persisted(**kw)\n"
        "assert not stream_programs_persisted(chunk_bytes=1 << 15,\n"
        "                                     u_cap=1 << 10)\n"
        "print('probe-ok')\n"
    )
    env = dict(os.environ)
    env["DSI_AOT_CACHE_DIR"] = str(tmp_path / "aot")
    env["DSI_AOT_QUIET"] = "1"
    env.pop("XLA_FLAGS", None)  # single-device process, like the chip
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert p.stdout.strip().splitlines()[-1] == "probe-ok"
