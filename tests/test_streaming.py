"""Streaming SPMD path: corpus size decoupled from device/host memory.

Oracle discipline as everywhere else: exact agreement with a host Counter
over the Go tokenizer semantics, and with the one-shot sharded path.
"""

import collections
import re

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.mr.worker import ihash
from dsi_tpu.parallel.shuffle import default_mesh, wordcount_sharded
from dsi_tpu.parallel.streaming import (
    _MAX_BACKOFF,
    _TokenTooLong,
    _cut_at_boundary,
    batch_stream,
    stream_files,
    wordcount_streaming,
)

WORDS = re.compile(r"[A-Za-z]+")


def _mesh():
    return default_mesh(8)


def test_batches_never_split_tokens():
    text = ("alpha beta gamma delta epsilon " * 400).encode()
    # Tiny chunks force cuts everywhere; every cut must land on a boundary.
    rebuilt = []
    for batch in batch_stream([text], n_dev=4, chunk_bytes=64):
        for row in batch:
            rebuilt.append(bytes(row).rstrip(b"\x00"))
    got = collections.Counter()
    for piece in rebuilt:
        got.update(WORDS.findall(piece.decode()))
    assert got == collections.Counter(WORDS.findall(text.decode()))


def test_streaming_matches_counter_and_partitions():
    text = ("the quick brown fox jumps over the lazy dog " * 3000).encode()
    blocks = [text[i:i + 7919] for i in range(0, len(text), 7919)]
    res = wordcount_streaming(blocks, mesh=_mesh(), n_reduce=10,
                              chunk_bytes=1 << 12, u_cap=1 << 10)
    assert res is not None
    want = collections.Counter(WORDS.findall(text.decode()))
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, p) in res.items():
        assert p == ihash(w) % 10


def test_streaming_matches_one_shot_sharded():
    rng = np.random.default_rng(7)
    words = ["tpu", "stream", "carry", "boundary", "chunk", "merge",
             "accumulate", "wave"]
    text = " ".join(words[i] for i in rng.integers(0, 8, 20_000)).encode()
    mesh = _mesh()
    stream = wordcount_streaming([text], mesh=mesh, n_reduce=10,
                                 chunk_bytes=1 << 12, u_cap=1 << 10)
    oneshot = wordcount_sharded(text, mesh=mesh, n_reduce=10, u_cap=1 << 10)
    assert stream is not None and oneshot is not None
    assert stream == oneshot


def _cut_reference(buf, size):
    """The pre-vectorization per-byte backoff loop, kept as the oracle."""
    def letter(b):
        return (65 <= b <= 90) or (97 <= b <= 122)

    if len(buf) <= size:
        return len(buf)
    c = size
    while c > 0 and letter(buf[c - 1]) and letter(buf[c]):
        c -= 1
        if size - c > _MAX_BACKOFF:
            raise _TokenTooLong
    return c


def test_cut_at_boundary_matches_scalar_reference():
    """The vectorized cut must agree with the per-byte reference loop on
    random byte soup, long letter runs at every offset around the cut,
    and the too-long-token escape."""
    rng = np.random.default_rng(11)
    for size in (8, 64, 97, 256):
        for _ in range(40):
            n = size + int(rng.integers(1, 2 * _MAX_BACKOFF + 8))
            buf = bytearray(rng.integers(0, 256, size=n, dtype=np.uint8)
                            .tobytes())
            # bias toward letters so long runs actually occur
            if rng.random() < 0.5:
                run = int(rng.integers(1, 2 * _MAX_BACKOFF))
                at = int(rng.integers(0, max(1, n - run)))
                buf[at:at + run] = b"q" * run
            try:
                want = _cut_reference(buf, size)
            except _TokenTooLong:
                with pytest.raises(_TokenTooLong):
                    _cut_at_boundary(buf, size)
                continue
            assert _cut_at_boundary(buf, size) == want
    # short-buffer fast path
    assert _cut_at_boundary(bytearray(b"abc"), 8) == 3


def test_pipeline_depth_parity_and_deferred_replay():
    """depth=1, depth=3, and a host Counter must agree bit-for-bit on a
    stream that forces a mid-stream capacity overflow — the deferred
    check replays the overflowing step exactly once (counts would be
    doubled by a merge-then-replay bug, halved by a dropped step)."""
    rng = np.random.default_rng(23)
    small = ["aa", "bb", "cc", "dd"]
    big = ["w%03d" % i for i in range(700)]  # > u_cap uniques per chunk
    blocks = []
    for i in range(12):
        vocab = small if i < 6 else big  # overflow arrives mid-stream
        picks = rng.integers(0, len(vocab), 400)
        blocks.append((" ".join(vocab[j] for j in picks) + "\n").encode())
    text = b"".join(blocks)
    want = dict(collections.Counter(WORDS.findall(text.decode())))
    mesh = _mesh()
    results, stats = {}, {}
    for d in (1, 3):
        st: dict = {}
        res = wordcount_streaming(list(blocks), mesh=mesh, n_reduce=10,
                                  chunk_bytes=1 << 11, u_cap=64, depth=d,
                                  pipeline_stats=st)
        assert res is not None
        results[d], stats[d] = res, st
    assert {w: c for w, (c, _) in results[3].items()} == want
    assert results[1] == results[3]  # bit-identical dicts, partitions too
    assert stats[3]["replays"] >= 1  # the deferred check actually fired
    assert stats[3]["steps"] == stats[1]["steps"]


def test_pipeline_keeps_tail_batch_and_step_count():
    """depth>1 must retire every step including the partial tail batch —
    a window-drain bug would drop the newest steps, a reorder would still
    show up as wrong counts for the tail-only marker word."""
    filler = ("lorem ipsum dolor sit amet " * 40).encode()
    blocks = [filler] * 7 + [b"zzzmarker zzzmarker zzzmarker"]
    text = b"".join(blocks)
    want = dict(collections.Counter(WORDS.findall(text.decode())))
    st: dict = {}
    res = wordcount_streaming(list(blocks), mesh=_mesh(), n_reduce=10,
                              chunk_bytes=1 << 10, u_cap=1 << 8, depth=3,
                              pipeline_stats=st)
    assert res is not None
    assert {w: c for w, (c, _) in res.items()} == want
    assert res["zzzmarker"][0] == 3  # the tail-only word survived
    n_rows = sum(len(b) for b in blocks) // (1 << 10) + 1
    assert st["steps"] >= max(1, n_rows // 8)  # tail batch was a step


def test_pipeline_buffer_accounting_stays_bounded():
    """Host batch buffers are recycled (O(depth) allocations however long
    the stream) and the device in-flight window never exceeds depth —
    the HBM-residency bound the design promises."""
    line = ("alpha beta gamma delta " * 30).encode()
    blocks = [line] * 200
    for d in (1, 2, 3):
        st: dict = {}
        res = wordcount_streaming(list(blocks), mesh=_mesh(), n_reduce=10,
                                  chunk_bytes=1 << 10, u_cap=1 << 8,
                                  depth=d, pipeline_stats=st)
        assert res is not None
        assert st["steps"] > 2 * d  # long enough to prove recycling
        assert st["max_inflight_chunks"] <= d
        assert st["batch_allocs"] <= 2 * d + 3
        assert st["replays"] == 0


def test_pipeline_sticky_rung_bounds_replays():
    """A stream that token-overflows the optimistic frac on EVERY chunk
    (dense single-letter words: tokens ≈ n/2 > t_cap at frac 4) must
    replay at most the in-flight window, not every step: the cleared
    (grouper, frac) rung sticks for later dispatches just like a widened
    capacity."""
    text = b"a b c d e f g h " * 6000
    want = dict(collections.Counter(WORDS.findall(text.decode())))
    st: dict = {}
    res = wordcount_streaming([text], mesh=_mesh(), n_reduce=10,
                              chunk_bytes=1 << 11, u_cap=1 << 8, depth=3,
                              pipeline_stats=st)
    assert res is not None
    assert {w: c for w, (c, _) in res.items()} == want
    assert st["steps"] > 3  # long enough that stickiness matters
    assert 1 <= st["replays"] <= 3  # bounded by the window, not the stream


def test_pipeline_depth_env_default(monkeypatch):
    """DSI_STREAM_PIPELINE_DEPTH is the default window for callers that
    pass no depth; an explicit depth always wins."""
    monkeypatch.setenv("DSI_STREAM_PIPELINE_DEPTH", "3")
    st: dict = {}
    res = wordcount_streaming([b"one two three " * 200], mesh=_mesh(),
                              chunk_bytes=1 << 10, u_cap=1 << 8,
                              pipeline_stats=st)
    assert res is not None and st["depth"] == 3
    st = {}
    res = wordcount_streaming([b"one two three " * 200], mesh=_mesh(),
                              chunk_bytes=1 << 10, u_cap=1 << 8, depth=1,
                              pipeline_stats=st)
    assert res is not None and st["depth"] == 1


def test_streaming_non_ascii_falls_back():
    blocks = [b"plain words ", "café".encode("utf-8"), b" more words"]
    assert wordcount_streaming(blocks, mesh=_mesh(),
                               chunk_bytes=1 << 10, u_cap=1 << 8) is None


def test_streaming_giant_token_falls_back():
    # A letter run far beyond the 64-byte device word limit, positioned to
    # span a chunk cut: the streaming path must hand the job to the host.
    blocks = [b"ok words here ", b"x" * 5000, b" tail"]
    assert wordcount_streaming(blocks, mesh=_mesh(),
                               chunk_bytes=1 << 10, u_cap=1 << 8) is None


def test_stream_files_separates_documents(tmp_path):
    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_bytes(b"ends with word")
    b.write_bytes(b"word starts here")
    data = b"".join(stream_files([str(a), str(b)]))
    got = collections.Counter(WORDS.findall(data.decode()))
    # "word" twice — NOT a merged "wordword" at the file seam.
    assert got["word"] == 2 and "wordword" not in got


def test_wcstream_cli_matches_sequential_oracle(tmp_path, monkeypatch):
    """VERDICT r2 task 4: the streaming path must be reachable without
    importing internals — the wcstream CLI end-to-end vs the oracle."""
    from dsi_tpu.cli import wcstream
    from tests.harness import merged_output, oracle_output

    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=3,
                          file_size=20_000)
    want = oracle_output("wc", files, str(tmp_path))
    wd = tmp_path / "out"
    wd.mkdir()
    rc = wcstream.main(["--nreduce", "10", "--chunk-bytes", "4096",
                        "--check", "--workdir", str(wd)] + files)
    assert rc == 0  # --check exits 2 on a parity failure
    assert merged_output(str(wd)) == want


def test_wcstream_cli_host_fallback(tmp_path):
    from dsi_tpu.cli import wcstream
    from tests.harness import merged_output, oracle_output

    f = tmp_path / "in.txt"
    f.write_text("café words café and more words", encoding="utf-8")
    want = oracle_output("wc", [str(f)], str(tmp_path))
    wd = tmp_path / "out"
    wd.mkdir()
    rc = wcstream.main(["--workdir", str(wd), str(f)])
    assert rc == 0
    assert merged_output(str(wd)) == want


@pytest.mark.slow
def test_streaming_100mb_bounded_memory():
    """>=100 MB through the 8-device virtual mesh with bounded footprint:
    the corpus is a generator (never materialised), the accumulator is
    vocabulary-bounded, and every step reuses one compiled program."""
    from dsi_tpu.utils.corpus import generate_file

    base_path = "/tmp/dsi-stream-base.bin"
    generate_file(base_path, (1 << 20) - 1, seed=99)
    with open(base_path, "rb") as f:
        base = f.read() + b"\n"  # newline: no cross-repeat token merge
    repeats = 100  # ~100 MB total

    def blocks():
        for _ in range(repeats):
            yield base

    res = wordcount_streaming(blocks(), mesh=_mesh(), n_reduce=10,
                              chunk_bytes=1 << 20, u_cap=1 << 16)
    assert res is not None
    base_counts = collections.Counter(WORDS.findall(base.decode()))
    want = {w: c * repeats for w, c in base_counts.items()}
    assert {w: c for w, (c, _) in res.items()} == want


def test_streaming_aot_path_matches_counter(tmp_path, monkeypatch):
    """The aot=True bench path (AOT-cached step + full-capacity pack) on a
    single-device mesh — the exact configuration bench.py's stream row
    runs on the chip — must agree with the Counter oracle, and the warm
    pass must cover every program the stream then executes (zero compiles
    after warming)."""
    from dsi_tpu.backends import aotcache
    from dsi_tpu.parallel.streaming import warm_stream_aot

    monkeypatch.setenv("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    mesh = default_mesh(1)
    warm_stream_aot(mesh=mesh, chunk_bytes=1 << 14, caps=(1 << 10,))
    compiles_after_warm = aotcache.stats["compiles"]
    text = ("portable exact streaming " * 900).encode()
    res = wordcount_streaming([text], mesh=mesh, n_reduce=10,
                              chunk_bytes=1 << 14, u_cap=1 << 10, aot=True)
    assert res is not None
    want = collections.Counter(WORDS.findall(text.decode()))
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, part) in res.items():
        assert part == ihash(w) % 10
    assert aotcache.stats["compiles"] == compiles_after_warm


def test_stream_programs_persisted_probe_mirrors_warm(tmp_path):
    """stream_programs_persisted must hit the exact keys warm_stream_aot
    persists — a drifted mirror makes the bench silently skip its stream
    row forever on fully-warmed machines.  Single-device subprocess:
    persistence is disabled on the 8-device test mesh by design."""
    import os
    import subprocess
    import sys

    child = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from dsi_tpu.parallel.streaming import (\n"
        "    stream_programs_persisted, warm_stream_aot)\n"
        "kw = dict(chunk_bytes=1 << 14, u_cap=1 << 10)\n"
        "assert not stream_programs_persisted(**kw)\n"
        "warm_stream_aot(chunk_bytes=1 << 14, caps=(1 << 10,))\n"
        "assert stream_programs_persisted(**kw)\n"
        "assert not stream_programs_persisted(chunk_bytes=1 << 15,\n"
        "                                     u_cap=1 << 10)\n"
        "# Device-accumulate extension: the fold programs are extra keys\n"
        "# (the step warm above must NOT satisfy the stricter probe).\n"
        "assert not stream_programs_persisted(device_accumulate=True, **kw)\n"
        "warm_stream_aot(chunk_bytes=1 << 14, caps=(1 << 10,),\n"
        "                device_accumulate=True)\n"
        "assert stream_programs_persisted(device_accumulate=True, **kw)\n"
        "print('probe-ok')\n"
    )
    env = dict(os.environ)
    env["DSI_AOT_CACHE_DIR"] = str(tmp_path / "aot")
    env["DSI_AOT_QUIET"] = "1"
    env.pop("XLA_FLAGS", None)  # single-device process, like the chip
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert p.stdout.strip().splitlines()[-1] == "probe-ok"
