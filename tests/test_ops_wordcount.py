"""Kernel-vs-oracle tests for the TPU word-count ops (CPU-mesh JAX).

Oracle: the host wc app semantics (``mrapps/wc.go:21-34`` — maximal letter
runs) via regex + Counter, and the reference ``ihash`` via the pure-Python
FNV in ``dsi_tpu.mr.worker``.
"""

from __future__ import annotations

import collections
import random
import string

import pytest

from dsi_tpu.apps.wc import tokenize
from dsi_tpu.mr.worker import ihash
from dsi_tpu.ops.wordcount import count_words_host_result, count_words_many


def oracle_counts(text: str):
    return collections.Counter(tokenize(text))


def check(text: str):
    res = count_words_host_result(text.encode("ascii"))
    assert res is not None
    expect = oracle_counts(text)
    got = {w: c for w, (c, _) in res.items()}
    assert got == dict(expect)
    for w, (_, h) in res.items():
        assert h == ihash(w), w


def test_simple():
    check("the quick brown fox jumps over the lazy dog the end")


def test_empty_and_no_letters():
    assert count_words_host_result(b"") == {}
    assert count_words_host_result(b"123 456 !!! \n\t 789") == {}


def test_edges():
    check("word")                      # single word, no separator
    check("a")                         # 1-byte word
    check("a b a b a")                 # minimal spacing (token-cap worst case)
    check("end-of-buffer-word trailing")
    check("Capital capital CAPITAL cApItAl")
    check("under_score split3split digits123mixed")


def test_long_words_retry_wider_kernel():
    # > 16 bytes forces the 64-byte kernel retry path.
    long_word = "supercalifragilisticexpialidocious"  # 34 letters
    check(f"short {long_word} short {long_word}")


def test_very_long_word_falls_back():
    # > 64 letters: exact handling requires the host path.
    assert count_words_host_result(b"x" * 100) is None


def test_non_ascii_falls_back():
    assert count_words_host_result("héllo world".encode("utf-8")) is None


def test_random_text():
    rng = random.Random(7)
    seps = " \n\t.,;:!?0123456789_"
    pieces = []
    for _ in range(5000):
        pieces.append("".join(rng.choice(string.ascii_letters)
                              for _ in range(rng.randint(1, 14))))
        pieces.append(rng.choice(seps) * rng.randint(1, 3))
    check("".join(pieces))


@pytest.mark.parametrize("size", [0, 1, 255, 256, 257, 4096])
def test_padding_boundaries(size):
    rng = random.Random(size)
    text = "".join(rng.choice("ab c") for _ in range(size))
    check(text)


def test_count_words_many_pipelined():
    """Pipelined multi-split path: same results as per-split calls,
    including per-split fallbacks and overflow retries."""
    datas = [
        b"alpha beta alpha",
        "héllo".encode("utf-8"),          # non-ASCII -> None
        b"abcdefghijklmnopqrstuvwx " * 40,      # 24-byte word -> wide retry
        b"a b c " * 300,                        # token-dense -> t_cap retry
        b"",
    ]
    many = count_words_many(datas)
    solo = [count_words_host_result(d) for d in datas]
    assert many == solo
    assert many[1] is None and many[0]["alpha"] == (2, many[0]["alpha"][1])


def test_zero_capacity_start_terminates():
    """A u_cap of 0 must widen through the retry ladder (floor of 1), not
    re-run the same zero-capacity kernel forever — in both entry points."""
    res = count_words_host_result(b"alpha beta alpha", u_cap=0)
    assert res is not None and res["alpha"][0] == 2 and res["beta"][0] == 1
    many = count_words_many([b"alpha beta alpha", b"beta"], u_cap=0)
    assert [m["beta"][0] for m in many] == [1, 1]


def test_pack_key_lanes_order_and_roundtrip():
    """Packed uint64 sort order must equal the unpacked lexicographic
    order, and unpack must invert pack — for even and odd lane counts,
    including PAD rows."""
    import jax.numpy as jnp
    import numpy as np

    from dsi_tpu.ops.wordcount import (_PAD_KEY, pack_key_lanes,
                                       unpack_key_rows)
    from dsi_tpu.utils.jaxcompat import enable_x64

    rng = np.random.default_rng(3)
    for k in (1, 2, 3, 4, 16):
        n = 257
        cols_np = rng.integers(0, 0x7F7F7F80, size=(k, n), dtype=np.uint32)
        # sprinkle PAD rows (all lanes 0xFFFFFFFF), which must sort last
        pad_rows = rng.choice(n, size=16, replace=False)
        for j in range(k):
            cols_np[j, pad_rows] = _PAD_KEY
        cols = tuple(jnp.asarray(cols_np[j]) for j in range(k))

        # Eager u64 ops need the scope held across every op touching the
        # packed values (jaxcompat.x64_scoped rationale): outside it the
        # stack/asarray would silently truncate the high lanes to u32.
        with enable_x64(True):
            packed = pack_key_lanes(cols)
            assert len(packed) == (k + 1) // 2
            # roundtrip
            rows64 = jnp.stack(packed, axis=1)
            back = np.asarray(unpack_key_rows(rows64, k))
            packed_np = [np.asarray(p) for p in packed]
        assert np.array_equal(back, cols_np.T)
        # order: argsort by packed columns == lexsort by original lanes
        order_packed = np.lexsort(tuple(reversed(packed_np)))
        order_lanes = np.lexsort(tuple(reversed(cols_np)))
        assert np.array_equal(cols_np.T[order_packed],
                              cols_np.T[order_lanes])
        # PAD rows sort last under the packed order
        assert set(order_packed[-16:]) == set(pad_rows)


# ── hash grouper (round 5): exactness under forced collisions ──────────


def _fnv1a(w: str) -> int:
    h = 0x811C9DC5
    for ch in w.encode():
        h = ((h ^ ch) * 0x01000193) & 0xFFFFFFFF
    return h


def _colliding_words(mask: int, count: int = 2):
    """Distinct lowercase words sharing fnv1a low bits (the hash
    grouper's level-1 bucket index at small chunk shapes)."""
    seen: dict = {}
    import itertools

    for tup in itertools.product(string.ascii_lowercase, repeat=3):
        w = "".join(tup)
        b = _fnv1a(w) & mask
        seen.setdefault(b, []).append(w)
        if len(seen[b]) >= count:
            return seen[b][:count]
    raise AssertionError("no collision found")


def test_hash_grouper_dirty_bucket_exact(monkeypatch):
    """Two distinct words sharing a level-1 bucket must be separated by
    the dirty-repair sort, not merged (exactness does not depend on hash
    luck)."""
    monkeypatch.setenv("DSI_WC_GROUPER", "hash")
    # 4 KB pad -> t_cap = 1025 -> n_buckets = 1 << max(10, 10-1) = 1024.
    w1, w2 = _colliding_words(1023)
    text = (f"{w1} {w2} " * 150 + f"{w1} filler words here").ljust(3000)
    check(text)


def test_hash_grouper_dirty_overflow_falls_back(monkeypatch):
    """More colliding tokens than the dirty buffer holds: group_overflow
    must route the chunk to the sort grouper and stay exact."""
    monkeypatch.setenv("DSI_WC_GROUPER", "hash")
    w1, w2 = _colliding_words(1023)
    # d_cap = max(256, t_cap//16) = 256 at this shape; 600 dirty tokens
    # overflow it.
    text = f"{w1} {w2} " * 300
    check(text)


def test_hash_grouper_matches_sort_on_random_text(monkeypatch):
    rng = random.Random(11)
    words = ["".join(rng.choices(string.ascii_lowercase, k=rng.randint(1, 12)))
             for _ in range(400)]
    text = " ".join(rng.choice(words) for _ in range(5000))
    monkeypatch.setenv("DSI_WC_GROUPER", "hash")
    rh = count_words_host_result(text.encode())
    monkeypatch.setenv("DSI_WC_GROUPER", "sort")
    rs = count_words_host_result(text.encode())
    assert rh == rs and rh is not None
