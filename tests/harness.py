"""Shared helpers for the differential end-to-end tests.

This is the Python form of ``main/test-mr.sh``'s core loop: fresh sandbox,
oracle run, 1 coordinator + N workers, merge ``sort mr-out* | grep .`` and
byte-compare with the oracle output (test-mr.sh:13-53).
"""

from __future__ import annotations

import glob
import os
import threading
import time
from typing import List

from dsi_tpu.config import JobConfig
from dsi_tpu.mr.coordinator import make_coordinator
from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.sequential import run_sequential
from dsi_tpu.mr.worker import worker_loop


def merged_output(workdir: str) -> List[str]:
    """sort mr-out* | grep .  (test-mr.sh:52 — empty lines dropped so
    per-partition boundaries don't matter)."""
    lines: List[str] = []
    for p in sorted(glob.glob(os.path.join(workdir, "mr-out-*"))):
        with open(p, encoding="utf-8") as f:
            lines.extend(l for l in f if l.strip())
    return sorted(lines)


def oracle_output(app: str, files, workdir: str) -> List[str]:
    mapf, reducef = load_plugin(app)
    out = os.path.join(workdir, "mr-correct.txt")
    run_sequential(mapf, reducef, files, out)
    with open(out, encoding="utf-8") as f:
        return sorted(l for l in f if l.strip())


def run_distributed_threads(app: str, files, workdir: str, n_workers: int = 3,
                            n_reduce: int = 10, timeout_s: float = 60.0,
                            task_timeout_s: float = 10.0) -> None:
    """In-process distributed run: coordinator + worker threads sharing cfg."""
    cfg = JobConfig(n_reduce=n_reduce, workdir=workdir,
                    task_timeout_s=task_timeout_s,
                    socket_path=os.path.join(workdir, "mr.sock"),
                    wait_sleep_s=0.05)
    mapf, reducef = load_plugin(app)
    c = make_coordinator(files, n_reduce, cfg)
    try:
        workers = [threading.Thread(target=worker_loop, args=(mapf, reducef, cfg),
                                    daemon=True)
                   for _ in range(n_workers)]
        for w in workers:
            w.start()
        deadline = time.time() + timeout_s
        while not c.done():
            if time.time() > deadline:
                raise TimeoutError("job did not finish in time")
            time.sleep(0.05)
        for w in workers:
            w.join(timeout=10.0)
    finally:
        c.close()
