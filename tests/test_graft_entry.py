"""Driver entry-point contract tests.

The driver runs ``entry()`` (single-device compile check) and
``dryrun_multichip(n)`` (virtual 8-device mesh) and records stdout as the
round's MULTICHIP evidence artifact — rc=0 with an empty tail proved
nothing (ADVICE r2), so the dryrun must print self-evidencing parity lines.
"""

import sys


def test_dryrun_multichip_prints_evidence(capsys):
    sys.modules.pop("__graft_entry__", None)
    import __graft_entry__ as g

    g.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "wordcount_sharded over 8-device mesh" in out
    assert "parity OK" in out
    assert "tfidf_sharded" in out
    assert "wordcount_streaming" in out


def test_entry_returns_jittable(capsys):
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out is not None
