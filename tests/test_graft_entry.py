"""Driver entry-point contract tests.

The driver runs ``entry()`` (single-device compile check) and
``dryrun_multichip(n)`` (virtual 8-device mesh) and records stdout as the
round's MULTICHIP evidence artifact — rc=0 with an empty tail proved
nothing (ADVICE r2), so the dryrun must print self-evidencing parity lines.
"""

import sys


def test_dryrun_multichip_prints_evidence(capsys):
    sys.modules.pop("__graft_entry__", None)
    import __graft_entry__ as g

    g.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "wordcount_sharded over 8-device mesh" in out
    assert "parity OK" in out
    assert "tfidf_sharded" in out
    assert "wordcount_streaming" in out


def test_entry_returns_jittable(capsys):
    import jax
    import numpy as np

    import __graft_entry__ as g

    x64_before = jax.config.jax_enable_x64
    try:
        fn, args = g.entry()
        # The contract: the driver's compile check exercises the bench's
        # own corpus-scale program shape (8 x 2 MiB pieces), not a toy.
        assert len(args) == 8 and all(a.shape == (1 << 21,) for a in args)
        assert all(isinstance(a, np.ndarray) for a in args)  # no device puts
        out = np.asarray(jax.jit(fn)(*args))
        # corpus_kernel contract: flattened [u_cap, 2] rows + 4 scalars,
        # and the example text must produce counts with no escapes.
        nu, max_len, has_high, tok_of = (int(x) for x in out[-4:])
        assert nu > 0 and not has_high and not tok_of and max_len <= 16
    finally:
        # entry() flips the process-global x64 flag for the driver's
        # caller-owned jit; restore it so later tests in this process see
        # the suite's default config.
        jax.config.update("jax_enable_x64", x64_before)
