"""TF-IDF: host app semantics, framework e2e parity, and the SPMD
multi-chip path on the virtual 8-device mesh (BASELINE.json's last config).
"""

import math
import os

import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.apps import tfidf
from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output, run_distributed_threads


def test_map_emits_per_doc_term_counts():
    kva = tfidf.Map("docA", "red fish blue fish")
    assert [(kv.key, kv.value) for kv in kva] == [
        ("blue", "docA\t1"), ("fish", "docA\t2"), ("red", "docA\t1")]


def test_reduce_scores_and_formats(monkeypatch):
    monkeypatch.setenv("DSI_TFIDF_NDOCS", "4")
    out = tfidf.Reduce("fish", ["docB\t3", "docA\t2"])
    idf = math.log(4 / 2)
    assert out == f"2 docA:{2 * idf:.6f},docB:{3 * idf:.6f}"


def test_reduce_requires_ndocs(monkeypatch):
    monkeypatch.delenv("DSI_TFIDF_NDOCS", raising=False)
    with pytest.raises(RuntimeError, match="DSI_TFIDF_NDOCS"):
        tfidf.Reduce("w", ["d\t1"])


def test_idf_zero_when_word_in_every_doc(monkeypatch):
    monkeypatch.setenv("DSI_TFIDF_NDOCS", "2")
    out = tfidf.Reduce("the", ["a\t5", "b\t1"])
    assert out == "2 a:0.000000,b:0.000000"


def test_framework_e2e_matches_sequential_oracle(tmp_path, monkeypatch):
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=5,
                          file_size=20_000)
    monkeypatch.setenv("DSI_TFIDF_NDOCS", str(len(files)))
    want = oracle_output("tfidf", files, str(tmp_path))
    wd = tmp_path / "dist"
    os.makedirs(wd)
    run_distributed_threads("tfidf", files, str(wd), n_workers=3, n_reduce=7)
    assert merged_output(str(wd)) == want


def test_spmd_waves_match_sequential_oracle(tmp_path, monkeypatch):
    """The multi-chip path: 11 documents in waves over the 8-device virtual
    mesh (so the last wave has padding documents), all_to_all shuffle,
    host scoring — mr-out-* byte-identical to the sequential oracle."""
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import tfidf_sharded, write_tfidf_output

    n_docs = 11
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=n_docs,
                          file_size=3_000)
    monkeypatch.setenv("DSI_TFIDF_NDOCS", str(n_docs))
    want = oracle_output("tfidf", files, str(tmp_path))

    docs = []
    for p in files:
        with open(p, "rb") as f:
            docs.append(f.read())
    mesh = default_mesh(8)
    res = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 11)
    assert res is not None, "SPMD path unexpectedly fell back"
    wd = tmp_path / "spmd"
    os.makedirs(wd)
    write_tfidf_output(res, files, 10, str(wd))
    assert merged_output(str(wd)) == want


def test_spmd_falls_back_on_non_ascii(tmp_path):
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    docs = [b"plain ascii words", "unicode café text".encode("utf-8")]
    res = tfidf_sharded(docs, mesh=default_mesh(8), n_reduce=5,
                        u_cap=1 << 8)
    assert res is None  # caller must route the job to the host path
