"""TF-IDF: host app semantics, framework e2e parity, and the SPMD
multi-chip path on the virtual 8-device mesh (BASELINE.json's last config).
"""

import math
import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.apps import tfidf
from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output, run_distributed_threads


def test_map_emits_per_doc_term_counts():
    kva = tfidf.Map("docA", "red fish blue fish")
    assert [(kv.key, kv.value) for kv in kva] == [
        ("blue", "docA\t1"), ("fish", "docA\t2"), ("red", "docA\t1")]


def test_reduce_scores_and_formats(monkeypatch):
    monkeypatch.setenv("DSI_TFIDF_NDOCS", "4")
    out = tfidf.Reduce("fish", ["docB\t3", "docA\t2"])
    idf = math.log(4 / 2)
    assert out == f"2 docA:{2 * idf:.6f},docB:{3 * idf:.6f}"


def test_reduce_requires_ndocs(monkeypatch):
    monkeypatch.delenv("DSI_TFIDF_NDOCS", raising=False)
    with pytest.raises(RuntimeError, match="DSI_TFIDF_NDOCS"):
        tfidf.Reduce("w", ["d\t1"])


def test_idf_zero_when_word_in_every_doc(monkeypatch):
    monkeypatch.setenv("DSI_TFIDF_NDOCS", "2")
    out = tfidf.Reduce("the", ["a\t5", "b\t1"])
    assert out == "2 a:0.000000,b:0.000000"


def test_framework_e2e_matches_sequential_oracle(tmp_path, monkeypatch):
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=5,
                          file_size=20_000)
    monkeypatch.setenv("DSI_TFIDF_NDOCS", str(len(files)))
    want = oracle_output("tfidf", files, str(tmp_path))
    wd = tmp_path / "dist"
    os.makedirs(wd)
    run_distributed_threads("tfidf", files, str(wd), n_workers=3, n_reduce=7)
    assert merged_output(str(wd)) == want


def test_spmd_waves_match_sequential_oracle(tmp_path, monkeypatch):
    """The multi-chip path: 11 documents in waves over the 8-device virtual
    mesh (so the last wave has padding documents), all_to_all shuffle,
    host scoring — mr-out-* byte-identical to the sequential oracle."""
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import tfidf_sharded, write_tfidf_output

    n_docs = 11
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=n_docs,
                          file_size=3_000)
    monkeypatch.setenv("DSI_TFIDF_NDOCS", str(n_docs))
    want = oracle_output("tfidf", files, str(tmp_path))

    docs = []
    for p in files:
        with open(p, "rb") as f:
            docs.append(f.read())
    mesh = default_mesh(8)
    res = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 11)
    assert res is not None, "SPMD path unexpectedly fell back"
    wd = tmp_path / "spmd"
    os.makedirs(wd)
    write_tfidf_output(res, files, 10, str(wd))
    assert merged_output(str(wd)) == want


def test_wave_planning_tracks_per_wave_longest():
    from dsi_tpu.parallel.tfidf import plan_waves

    # One 10x outlier among uniform docs: longest-first order isolates it.
    lens = [1000] * 15 + [10_000]
    waves = plan_waves(lens, n_dev=8)
    assert len(waves) == 2
    assert waves[0][1] == 1 << 14      # the outlier's wave only
    assert waves[1][1] == 1 << 10      # uniform waves stay small
    assert 15 in waves[0][0]           # outlier scheduled first
    # Every doc appears exactly once across waves.
    seen = sorted(i for idxs, _ in waves for i in idxs)
    assert seen == list(range(16))


def test_outlier_document_compiles_few_shapes(tmp_path, monkeypatch):
    """VERDICT r2 task 5: one 10x outlier doc must not inflate every wave's
    buffers — <= 3 compiled shapes, and parity with the oracle holds."""
    import dsi_tpu.parallel.tfidf as m
    from dsi_tpu.parallel.shuffle import default_mesh

    rng = np.random.default_rng(5)
    vocab = ["".join(chr(97 + c) for c in rng.integers(0, 26, size=6))
             for _ in range(200)]

    def doc(n):
        return " ".join(vocab[i] for i in rng.integers(0, 200, n)).encode()

    docs = [doc(60) for _ in range(15)] + [doc(700)]  # one ~10x outlier
    sizes_used = []
    real_chunk = m._wave_chunk

    def spy(d, idxs, n_dev, size):
        sizes_used.append(size)
        return real_chunk(d, idxs, n_dev, size)

    monkeypatch.setattr(m, "_wave_chunk", spy)
    mesh = default_mesh(8)
    res = m.tfidf_sharded(docs, mesh=mesh, n_reduce=5, u_cap=1 << 11)
    assert res is not None
    assert len(set(sizes_used)) <= 3
    assert max(sizes_used) >= 4 * min(sizes_used)  # small waves stayed small

    # Exactness across the mixed shapes: df per word vs a host oracle.
    import collections
    import re
    want = collections.Counter()
    for d in docs:
        for w in set(re.findall(r"[A-Za-z]+", d.decode())):
            want[w] += 1
    got_df = {w: len(pairs) for w, (_, pairs) in res.items()}
    assert got_df == dict(want)


def test_partition_slices_union_equals_full_run(tmp_path):
    """The bounded-host-memory lever: per-partition-slice runs must union
    to exactly the full result, with each slice holding only its words."""
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=9,
                          file_size=2_500)
    docs = []
    for p in files:
        with open(p, "rb") as f:
            docs.append(f.read())
    mesh = default_mesh(8)
    full = tfidf_sharded(docs, mesh=mesh, n_reduce=6, u_cap=1 << 11)
    assert full is not None

    lo = tfidf_sharded(docs, mesh=mesh, n_reduce=6, u_cap=1 << 11,
                       partitions={0, 1, 2})
    hi = tfidf_sharded(docs, mesh=mesh, n_reduce=6, u_cap=1 << 11,
                       partitions={3, 4, 5})
    assert set(lo) | set(hi) == set(full)
    assert not set(lo) & set(hi)  # a word lives in exactly one slice
    for w, (part, pairs) in lo.items():
        assert part in {0, 1, 2}
        assert sorted(pairs) == sorted(full[w][1])
    for w, (part, pairs) in hi.items():
        assert part in {3, 4, 5}
        assert sorted(pairs) == sorted(full[w][1])


def test_spmd_falls_back_on_non_ascii(tmp_path):
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    docs = [b"plain ascii words", "unicode café text".encode("utf-8")]
    res = tfidf_sharded(docs, mesh=default_mesh(8), n_reduce=5,
                        u_cap=1 << 8)
    assert res is None  # caller must route the job to the host path


def test_packed_and_lazy_docs_match_dict(tmp_path):
    """FileDocs + packed=True must agree with resident docs + dict result
    (the GB-soak memory path, VERDICT r4 weakness #4)."""
    import numpy as np

    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import FileDocs, tfidf_sharded

    rng = random.Random(7)
    paths = []
    for i in range(5):
        # Letter-only words (digits split tokens: maximal letter runs).
        words = ["w" + "abcdefghij"[rng.randint(0, 9)]
                 + "xyzpq"[rng.randint(0, 4)] + "end"[rng.randint(0, 2):]
                 for _ in range(400)]
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes((" ".join(words)).encode())
        paths.append(str(p))
    docs = [open(p, "rb").read() for p in paths]
    mesh = default_mesh(4)
    want = tfidf_sharded(docs, mesh=mesh, n_reduce=10)
    lazy = FileDocs(paths)
    assert lazy.lengths == [len(d) for d in docs]
    got = tfidf_sharded(lazy, mesh=mesh, n_reduce=10, packed=True)
    assert got is not None and want is not None
    assert got.to_dict() == want
    # Point lookups agree and omit absent words.
    some = list(want)[:20] + ["notaword"]
    hits = got.lookup_many(some)
    assert "notaword" not in hits
    for w in some[:20]:
        assert hits[w] == want[w]
    # Vectorized invariant surface used by the soak.
    assert got.n_postings == sum(len(ps) for _, ps in want.values())
    assert (got.postings_per_word() >= 1).all()
