"""Unit tests for the vectorized host-side merge tables (parallel/merge.py).

These are pure-numpy properties (no mesh needed): the tables must agree
with a straightforward dict/Counter oracle on random inputs, across
compaction windows, mixed key widths, and count magnitudes past uint32.
"""

from __future__ import annotations

import random
from collections import Counter

import numpy as np
import pytest

from dsi_tpu.parallel.merge import PackedCounts, PostingsTable


def _pack_word(w: str, k: int) -> np.ndarray:
    """Big-endian uint32 lanes, zero-padded — the kernel's packing
    (ops/wordcount.py tokenize_group_core)."""
    raw = w.encode("ascii").ljust(4 * k, b"\0")
    return np.frombuffer(raw, dtype=">u4").astype(np.uint32)


def _rows(words, counts, k):
    keys = np.stack([_pack_word(w, k) for w in words])
    lens = np.array([len(w) for w in words], dtype=np.int32)
    cnts = np.array(counts, dtype=np.int64)
    parts = np.array([hash(w) % 10 for w in words], dtype=np.int32)
    return keys, lens, cnts, parts


def test_packed_counts_matches_counter_oracle():
    rng = random.Random(7)
    vocab = ["".join(rng.choices("abcdefgh", k=rng.randint(1, 12)))
             for _ in range(200)]
    oracle: Counter = Counter()
    acc = PackedCounts(compact_rows=64)  # force many compactions
    for _ in range(30):
        batch = rng.choices(vocab, k=rng.randint(1, 50))
        local = Counter(batch)
        words = sorted(local)
        acc.add(*_rows(words, [local[w] for w in words], k=4))
        oracle.update(local)
    out = acc.finalize()
    assert {w: c for w, (c, _) in out.items()} == dict(oracle)
    # partition column survives the merge and is per-word stable
    for w, (_, p) in out.items():
        assert p == hash(w) % 10


def test_packed_counts_mixed_key_widths():
    acc = PackedCounts()
    # same word arriving from a 16-byte rung (k=4) and a 64-byte rung
    # (k=16) must merge: zero-padded lanes agree beyond the word
    acc.add(*_rows(["alpha", "beta"], [2, 3], k=4))
    acc.add(*_rows(["alpha", "gamma"], [5, 7], k=16))
    out = acc.finalize()
    assert {w: c for w, (c, _) in out.items()} == {
        "alpha": 7, "beta": 3, "gamma": 7}


def test_packed_counts_empty_and_large_counts():
    assert PackedCounts().finalize() == {}
    acc = PackedCounts()
    big = (1 << 31) + 5
    for _ in range(3):
        acc.add(*_rows(["x"], [big], k=4))
    assert acc.finalize()["x"][0] == 3 * big  # int64, no uint32 wrap


def test_packed_counts_ignores_empty_batches():
    acc = PackedCounts()
    acc.add(np.zeros((0, 4), np.uint32), np.zeros(0, np.int32),
            np.zeros(0, np.int64), np.zeros(0, np.int32))
    assert acc.finalize() == {}


def test_postings_table_matches_dict_oracle():
    rng = random.Random(11)
    vocab = ["".join(rng.choices("mnopqr", k=rng.randint(1, 8)))
             for _ in range(60)]
    kk = 4
    oracle: dict = {}
    table = PostingsTable()
    for wave in range(10):
        rows = []
        for w in set(rng.choices(vocab, k=20)):
            tf = rng.randint(1, 9)
            doc = rng.randint(0, 30)
            part = hash(w) % 10
            row = np.concatenate([
                _pack_word(w, kk),
                np.array([len(w), tf, doc, part], dtype=np.uint32)])
            rows.append(row)
            ent = oracle.setdefault(w, (part, []))
            ent[1].append((doc, tf))
        table.add(np.stack(rows), kk)
    out = table.finalize()
    assert set(out) == set(oracle)
    for w in oracle:
        assert out[w][0] == oracle[w][0]
        assert sorted(out[w][1]) == sorted(oracle[w][1])


def test_postings_table_empty_and_width_guard():
    assert PostingsTable().finalize() == {}
    t = PostingsTable()
    t.add(np.zeros((1, 8), np.uint32), 4)
    with pytest.raises(ValueError):
        t.add(np.zeros((1, 20), np.uint32), 16)
