"""Property-based fuzzing of the exactness-critical paths.

The framework's central promise is byte-exact parity with the reference
semantics for ARBITRARY inputs (SURVEY.md §4's differential-oracle
discipline).  These properties throw adversarial inputs — random bytes,
pathological token shapes, hostile JSON strings — at the device kernels and
the native codec and require agreement with the trivially-correct host
implementations.
"""

import collections
import json
import os
import re

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

jax = pytest.importorskip("jax")

from dsi_tpu import native
from dsi_tpu.mr.worker import ihash
from dsi_tpu.ops.grepk import grep_host_result, is_literal_pattern
from dsi_tpu.ops.wordcount import count_words_host_result

ASCII_WORDS = re.compile(r"[A-Za-z]+")

# Text drawn from a tiny alphabet maximizes boundary collisions: runs of
# letters vs separators, words at chunk edges, token-dense pathologies.
dense_text = st.text(alphabet="ab XY.\n\t0", min_size=0, max_size=2000)
ascii_bytes = st.binary(min_size=0, max_size=1500).map(
    lambda b: bytes(x & 0x7F for x in b))


@settings(max_examples=60, deadline=None)
@given(dense_text)
def test_wordcount_kernel_matches_counter(text):
    data = text.encode("ascii")
    res = count_words_host_result(data, u_cap=256)
    assert res is not None
    want = collections.Counter(ASCII_WORDS.findall(text))
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, h) in res.items():
        assert h == ihash(w)


@settings(max_examples=40, deadline=None)
@given(ascii_bytes)
def test_wordcount_kernel_arbitrary_ascii_bytes(data):
    res = count_words_host_result(data, u_cap=256)
    assert res is not None
    want = collections.Counter(
        ASCII_WORDS.findall(data.decode("ascii", "ignore")))
    # NUL and control bytes are non-letters for the kernel; the regex over
    # the decoded text sees the same token boundaries.
    assert {w: c for w, (c, _) in res.items()} == dict(want)


# Adversarial Unicode alphabet for tokenizer parity: ASCII letters and
# separators, Nl numeral letters (Roman numerals — "letters" to Python's \w
# but NOT to Go's unicode.IsLetter), No numerics, combining marks, CJK,
# Greek, a Latin-1 ordinal (Lo — a real letter), digits and punctuation.
unicode_text = st.text(
    alphabet="ab XY.\n0Ⅳⅻ²½ªµ漢語αβ́̈_-", min_size=0, max_size=800)


def go_letter_runs(text):
    """Rune-level oracle for strings.FieldsFunc(s, !unicode.IsLetter)
    (mrapps/wc.go:23): maximal runs of Unicode category-L code points."""
    import unicodedata

    out, cur = [], []
    for ch in text:
        if unicodedata.category(ch).startswith("L"):
            cur.append(ch)
        elif cur:
            out.append("".join(cur))
            cur = []
    if cur:
        out.append("".join(cur))
    return out


@settings(max_examples=80, deadline=None)
@given(unicode_text)
def test_tokenizer_matches_go_isletter_on_unicode(text):
    from dsi_tpu.apps.wc import tokenize

    assert tokenize(text) == go_letter_runs(text)


@settings(max_examples=40, deadline=None)
@given(unicode_text)
def test_wc_map_host_path_unicode_parity(text):
    """The full host Map (the kernel's fallback contract) must produce
    exactly the Go-semantics words on non-ASCII text too."""
    from dsi_tpu.apps import wc

    assert [kv.key for kv in wc.Map("f", text)] == go_letter_runs(text)


@settings(max_examples=40, deadline=None)
@given(dense_text, st.text(alphabet="abX .", min_size=1, max_size=6))
def test_grep_kernel_matches_regex(text, pat):
    data = text.encode("ascii")
    got = grep_host_result(data, pat)
    if not is_literal_pattern(pat):
        assert got is None
        return
    want = [line for line in text.split("\n") if pat in line]
    assert got == want


json_strings = st.text(min_size=0, max_size=50)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.tuples(json_strings, json_strings), max_size=30))
def test_native_codec_never_diverges(tmp_path_factory, records):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    d = tmp_path_factory.mktemp("kv")
    path = os.path.join(str(d), "kv")
    with open(path, "w") as f:
        for k, v in records:
            try:
                f.write(json.dumps({"Key": k, "Value": v}) + "\n")
            except (ValueError, UnicodeEncodeError):
                return  # unencodable (should not happen for str)
    nat = native.decode_kv_file(path)
    py = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            py.append((obj["Key"], obj["Value"]))
    # native either agrees exactly or declines
    assert nat is None or nat == py


# ---- the whole-corpus single-program path (ops/corpus_wc.py) ----

from dsi_tpu.ops.corpus_wc import corpus_wordcount  # noqa: E402

corpus_lists = st.lists(dense_text, min_size=0, max_size=5)


def _longest_run(texts):
    return max((len(w) for t in texts for w in ASCII_WORDS.findall(t)),
               default=0)


@settings(max_examples=40, deadline=None)
@given(corpus_lists, st.booleans())
def test_corpus_wordcount_matches_counter(texts, pack6):
    raws = [t.encode("ascii") for t in texts]
    res = corpus_wordcount(raws, piece_size=1 << 12, u_cap=256, pack6=pack6)
    if _longest_run(texts) > 64:
        assert res is None  # documented escape: host path handles it
        return
    assert res is not None
    want = collections.Counter()
    for t in texts:
        want.update(ASCII_WORDS.findall(t))
    got = {w: c for w, (c, _) in res.to_dict().items()}
    assert got == dict(want)
    # Partition ids must be the reference ihash (mr/worker.go:33-37,76).
    for w, (_, part) in res.to_dict().items():
        assert part == ihash(w) % 10


@settings(max_examples=30, deadline=None)
@given(st.binary(min_size=1, max_size=1500))
def test_corpus_wordcount_arbitrary_bytes_exact_or_declines(data):
    res = corpus_wordcount([data], piece_size=1 << 12, u_cap=256)
    if any(b >= 0x80 for b in data) or _longest_run(
            [data.decode("latin-1")]) > 64:
        assert res is None  # non-ASCII or >64-byte word: host path decides
        return
    assert res is not None
    want = collections.Counter(ASCII_WORDS.findall(data.decode("ascii")))
    got = {w: c for w, (c, _) in res.to_dict().items()}
    assert got == dict(want)


@settings(max_examples=20, deadline=None)
@given(st.lists(st.text(alphabet="kq vw,", min_size=0, max_size=300),
                min_size=1, max_size=4))
def test_corpus_output_files_match_oracle_lines(tmp_path_factory, texts):
    from dsi_tpu.ops.corpus_wc import write_corpus_output

    tmp = tmp_path_factory.mktemp("fuzzout")
    raws = [t.encode() for t in texts]
    res = corpus_wordcount(raws, piece_size=1 << 12, u_cap=256)
    if _longest_run(texts) > 64:
        assert res is None
        return
    write_corpus_output(res, 10, str(tmp))
    got = []
    for r in range(10):
        with open(tmp / f"mr-out-{r}", encoding="utf-8") as f:
            got.extend(l for l in f if l.strip())
    want = collections.Counter()
    for t in texts:
        want.update(ASCII_WORDS.findall(t))
    assert sorted(got) == sorted(f"{w} {c}\n" for w, c in want.items())


# ---- the native map-side encoder (partition + escape + serialize) ----

kv_text = st.text(min_size=0, max_size=60)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.tuples(kv_text, kv_text), min_size=0, max_size=40),
       st.integers(min_value=1, max_value=12))
def test_native_encoder_blobs_roundtrip_and_partition(tmp_path_factory,
                                                      pairs, n_reduce):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    from dsi_tpu.mr.types import KeyValue

    kva = [KeyValue(k, v) for k, v in pairs]
    blobs = native.encode_partitions(kva, n_reduce)
    # st.text never generates surrogates, so None here could only be an
    # unexpected native failure — a silent pass would mask it.
    assert blobs is not None
    seen = []
    for r, blob in enumerate(blobs):
        # Split on \n only — the format's record delimiter (splitlines()
        # would also split on U+0085/U+2028 INSIDE raw-UTF-8 values).
        for line in blob.decode("utf-8").split("\n"):
            if not line:
                continue
            obj = json.loads(line)
            assert ihash(obj["Key"]) % n_reduce == r
            seen.append((obj["Key"], obj["Value"]))
    assert sorted(seen) == sorted(pairs)


def test_fuzz_hash_vs_sort_grouper_shapes(monkeypatch):
    """Dual-grouper equivalence across random shapes, vocabularies, and
    capacities — the hash grouper's bucket/dirty/overflow machinery must
    agree with the sort grouper everywhere (round 5)."""
    import random
    import string

    from dsi_tpu.ops.wordcount import count_words_host_result

    rng = random.Random(99)
    for trial in range(6):
        n_vocab = rng.choice([3, 40, 500, 3000])
        words = ["".join(rng.choices(string.ascii_letters,
                                     k=rng.randint(1, 14)))
                 for _ in range(n_vocab)]
        n_tokens = rng.choice([50, 2000, 20000])
        text = " ".join(rng.choice(words) for _ in range(n_tokens))
        u_cap = rng.choice([1 << 8, 1 << 12])
        monkeypatch.setenv("DSI_WC_GROUPER", "hash")
        rh = count_words_host_result(text.encode(), u_cap=u_cap)
        monkeypatch.setenv("DSI_WC_GROUPER", "sort")
        rs = count_words_host_result(text.encode(), u_cap=u_cap)
        assert rh == rs and rh is not None, (trial, n_vocab, n_tokens,
                                             u_cap)
