"""Property-based fuzzing of the exactness-critical paths.

The framework's central promise is byte-exact parity with the reference
semantics for ARBITRARY inputs (SURVEY.md §4's differential-oracle
discipline).  These properties throw adversarial inputs — random bytes,
pathological token shapes, hostile JSON strings — at the device kernels and
the native codec and require agreement with the trivially-correct host
implementations.
"""

import collections
import json
import os
import re

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

jax = pytest.importorskip("jax")

from dsi_tpu import native
from dsi_tpu.mr.worker import ihash
from dsi_tpu.ops.grepk import grep_host_result, is_literal_pattern
from dsi_tpu.ops.wordcount import count_words_host_result

ASCII_WORDS = re.compile(r"[A-Za-z]+")

# Text drawn from a tiny alphabet maximizes boundary collisions: runs of
# letters vs separators, words at chunk edges, token-dense pathologies.
dense_text = st.text(alphabet="ab XY.\n\t0", min_size=0, max_size=2000)
ascii_bytes = st.binary(min_size=0, max_size=1500).map(
    lambda b: bytes(x & 0x7F for x in b))


@settings(max_examples=60, deadline=None)
@given(dense_text)
def test_wordcount_kernel_matches_counter(text):
    data = text.encode("ascii")
    res = count_words_host_result(data, u_cap=256)
    assert res is not None
    want = collections.Counter(ASCII_WORDS.findall(text))
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, h) in res.items():
        assert h == ihash(w)


@settings(max_examples=40, deadline=None)
@given(ascii_bytes)
def test_wordcount_kernel_arbitrary_ascii_bytes(data):
    res = count_words_host_result(data, u_cap=256)
    assert res is not None
    want = collections.Counter(
        ASCII_WORDS.findall(data.decode("ascii", "ignore")))
    # NUL and control bytes are non-letters for the kernel; the regex over
    # the decoded text sees the same token boundaries.
    assert {w: c for w, (c, _) in res.items()} == dict(want)


# Adversarial Unicode alphabet for tokenizer parity: ASCII letters and
# separators, Nl numeral letters (Roman numerals — "letters" to Python's \w
# but NOT to Go's unicode.IsLetter), No numerics, combining marks, CJK,
# Greek, a Latin-1 ordinal (Lo — a real letter), digits and punctuation.
unicode_text = st.text(
    alphabet="ab XY.\n0Ⅳⅻ²½ªµ漢語αβ́̈_-", min_size=0, max_size=800)


def go_letter_runs(text):
    """Rune-level oracle for strings.FieldsFunc(s, !unicode.IsLetter)
    (mrapps/wc.go:23): maximal runs of Unicode category-L code points."""
    import unicodedata

    out, cur = [], []
    for ch in text:
        if unicodedata.category(ch).startswith("L"):
            cur.append(ch)
        elif cur:
            out.append("".join(cur))
            cur = []
    if cur:
        out.append("".join(cur))
    return out


@settings(max_examples=80, deadline=None)
@given(unicode_text)
def test_tokenizer_matches_go_isletter_on_unicode(text):
    from dsi_tpu.apps.wc import tokenize

    assert tokenize(text) == go_letter_runs(text)


@settings(max_examples=40, deadline=None)
@given(unicode_text)
def test_wc_map_host_path_unicode_parity(text):
    """The full host Map (the kernel's fallback contract) must produce
    exactly the Go-semantics words on non-ASCII text too."""
    from dsi_tpu.apps import wc

    assert [kv.key for kv in wc.Map("f", text)] == go_letter_runs(text)


@settings(max_examples=40, deadline=None)
@given(dense_text, st.text(alphabet="abX .", min_size=1, max_size=6))
def test_grep_kernel_matches_regex(text, pat):
    data = text.encode("ascii")
    got = grep_host_result(data, pat)
    if not is_literal_pattern(pat):
        assert got is None
        return
    want = [line for line in text.split("\n") if pat in line]
    assert got == want


json_strings = st.text(min_size=0, max_size=50)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.tuples(json_strings, json_strings), max_size=30))
def test_native_codec_never_diverges(tmp_path_factory, records):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    d = tmp_path_factory.mktemp("kv")
    path = os.path.join(str(d), "kv")
    with open(path, "w") as f:
        for k, v in records:
            try:
                f.write(json.dumps({"Key": k, "Value": v}) + "\n")
            except (ValueError, UnicodeEncodeError):
                return  # unencodable (should not happen for str)
    nat = native.decode_kv_file(path)
    py = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            py.append((obj["Key"], obj["Value"]))
    # native either agrees exactly or declines
    assert nat is None or nat == py


# ---- the whole-corpus single-program path (ops/corpus_wc.py) ----

from dsi_tpu.ops.corpus_wc import corpus_wordcount  # noqa: E402

corpus_lists = st.lists(dense_text, min_size=0, max_size=5)


def _longest_run(texts):
    return max((len(w) for t in texts for w in ASCII_WORDS.findall(t)),
               default=0)


@settings(max_examples=40, deadline=None)
@given(corpus_lists, st.booleans())
def test_corpus_wordcount_matches_counter(texts, pack6):
    raws = [t.encode("ascii") for t in texts]
    res = corpus_wordcount(raws, piece_size=1 << 12, u_cap=256, pack6=pack6)
    if _longest_run(texts) > 64:
        assert res is None  # documented escape: host path handles it
        return
    assert res is not None
    want = collections.Counter()
    for t in texts:
        want.update(ASCII_WORDS.findall(t))
    got = {w: c for w, (c, _) in res.to_dict().items()}
    assert got == dict(want)
    # Partition ids must be the reference ihash (mr/worker.go:33-37,76).
    for w, (_, part) in res.to_dict().items():
        assert part == ihash(w) % 10


@settings(max_examples=30, deadline=None)
@given(st.binary(min_size=1, max_size=1500))
def test_corpus_wordcount_arbitrary_bytes_exact_or_declines(data):
    res = corpus_wordcount([data], piece_size=1 << 12, u_cap=256)
    if any(b >= 0x80 for b in data) or _longest_run(
            [data.decode("latin-1")]) > 64:
        assert res is None  # non-ASCII or >64-byte word: host path decides
        return
    assert res is not None
    want = collections.Counter(ASCII_WORDS.findall(data.decode("ascii")))
    got = {w: c for w, (c, _) in res.to_dict().items()}
    assert got == dict(want)


@settings(max_examples=20, deadline=None)
@given(st.lists(st.text(alphabet="kq vw,", min_size=0, max_size=300),
                min_size=1, max_size=4))
def test_corpus_output_files_match_oracle_lines(tmp_path_factory, texts):
    from dsi_tpu.ops.corpus_wc import write_corpus_output

    tmp = tmp_path_factory.mktemp("fuzzout")
    raws = [t.encode() for t in texts]
    res = corpus_wordcount(raws, piece_size=1 << 12, u_cap=256)
    if _longest_run(texts) > 64:
        assert res is None
        return
    write_corpus_output(res, 10, str(tmp))
    got = []
    for r in range(10):
        with open(tmp / f"mr-out-{r}", encoding="utf-8") as f:
            got.extend(l for l in f if l.strip())
    want = collections.Counter()
    for t in texts:
        want.update(ASCII_WORDS.findall(t))
    assert sorted(got) == sorted(f"{w} {c}\n" for w, c in want.items())


# ---- the native map-side encoder (partition + escape + serialize) ----

kv_text = st.text(min_size=0, max_size=60)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.tuples(kv_text, kv_text), min_size=0, max_size=40),
       st.integers(min_value=1, max_value=12))
def test_native_encoder_blobs_roundtrip_and_partition(tmp_path_factory,
                                                      pairs, n_reduce):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    from dsi_tpu.mr.types import KeyValue

    kva = [KeyValue(k, v) for k, v in pairs]
    blobs = native.encode_partitions(kva, n_reduce)
    # st.text never generates surrogates, so None here could only be an
    # unexpected native failure — a silent pass would mask it.
    assert blobs is not None
    seen = []
    for r, blob in enumerate(blobs):
        # Split on \n only — the format's record delimiter (splitlines()
        # would also split on U+0085/U+2028 INSIDE raw-UTF-8 values).
        for line in blob.decode("utf-8").split("\n"):
            if not line:
                continue
            obj = json.loads(line)
            assert ihash(obj["Key"]) % n_reduce == r
            seen.append((obj["Key"], obj["Value"]))
    assert sorted(seen) == sorted(pairs)


def test_fuzz_hash_vs_sort_grouper_shapes(monkeypatch):
    """Dual-grouper equivalence across random shapes, vocabularies, and
    capacities — the hash grouper's bucket/dirty/overflow machinery must
    agree with the sort grouper everywhere (round 5)."""
    import random
    import string

    from dsi_tpu.ops.wordcount import count_words_host_result

    rng = random.Random(99)
    for trial in range(6):
        n_vocab = rng.choice([3, 40, 500, 3000])
        words = ["".join(rng.choices(string.ascii_letters,
                                     k=rng.randint(1, 14)))
                 for _ in range(n_vocab)]
        n_tokens = rng.choice([50, 2000, 20000])
        text = " ".join(rng.choice(words) for _ in range(n_tokens))
        u_cap = rng.choice([1 << 8, 1 << 12])
        monkeypatch.setenv("DSI_WC_GROUPER", "hash")
        rh = count_words_host_result(text.encode(), u_cap=u_cap)
        monkeypatch.setenv("DSI_WC_GROUPER", "sort")
        rs = count_words_host_result(text.encode(), u_cap=u_cap)
        assert rh == rs and rh is not None, (trial, n_vocab, n_tokens,
                                             u_cap)


# ---- checkpoint snapshot round-trips (dsi_tpu/ckpt + device services) ----
#
# The crash-resume property reduced to its serialization core: an
# ARBITRARY service state, imaged by checkpoint_state(), pushed through
# the real durable store (npz payload + CRC'd manifest on disk), and
# restored into a fresh service must drain BYTE-EQUAL to the original.
# Keys/counts are raw random bits (no decode step is involved in a
# drain), so this fuzzes the layout/dtype/sharding plumbing rather than
# tokenizer-reachable states only.

from hypothesis.extra import numpy as hnp  # noqa: E402

from dsi_tpu.ckpt import CheckpointStore  # noqa: E402
from dsi_tpu.device import (DeviceHistogram, DevicePostings,  # noqa: E402
                            DeviceTable, DeviceTopK)
from dsi_tpu.parallel.shuffle import default_mesh  # noqa: E402

_N_DEV, _CAP, _KK = 8, 8, 2


class _CaptureAcc:
    """Drain sink recording raw arrays — byte-level ground truth with
    no spelling decode in the way."""

    def __init__(self):
        self.rows = []

    def add(self, keys, lens, cnts, parts):
        self.rows.append((np.array(keys), np.array(lens),
                          np.array(cnts), np.array(parts)))

    def equal(self, other) -> bool:
        return len(self.rows) == len(other.rows) and all(
            all(np.array_equal(x, y) for x, y in zip(a, b))
            for a, b in zip(self.rows, other.rows))


def _table_img(draw):
    nrows = draw(hnp.arrays(np.int64, (_N_DEV,),
                            elements=st.integers(0, _CAP)))
    return {
        "keys": draw(hnp.arrays(np.uint32, (_N_DEV, _CAP, _KK),
                                elements=st.integers(0, 2 ** 32 - 1))),
        "lens": draw(hnp.arrays(np.int32, (_N_DEV, _CAP),
                                elements=st.integers(0, 8))),
        "cnts": draw(hnp.arrays(np.uint64, (_N_DEV, _CAP),
                                elements=st.integers(0, 2 ** 64 - 1))),
        "parts": draw(hnp.arrays(np.int32, (_N_DEV, _CAP),
                                 elements=st.integers(0, 9))),
        "tn": nrows.astype(np.int32),
        "nrows": nrows,
    }


def _roundtrip(tmpdir, svc_factory, img):
    """restore(img) -> checkpoint_state -> durable store -> restore into
    a fresh service; returns (original service, restored service)."""
    s1 = svc_factory()
    s1.restore_state(img)
    state = s1.checkpoint_state()
    store = CheckpointStore(str(tmpdir), "fuzz", {"shape": "fixed"})
    meta = {k: int(v) for k, v in state.items() if np.ndim(v) == 0}
    store.save({k: v for k, v in state.items() if np.ndim(v) > 0}, meta)
    loaded_meta, arrays = store.load_latest()
    arrays.update({k: np.array(v) for k, v in loaded_meta.items()})
    s2 = svc_factory()
    s2.restore_state(arrays)
    return s1, s2


@settings(max_examples=6, deadline=None)
@given(st.data())
def test_fuzz_device_table_snapshot_roundtrip(tmp_path_factory, data):
    mesh = default_mesh(_N_DEV)
    img = _table_img(data.draw)
    accs = []

    def factory():
        accs.append(_CaptureAcc())
        return DeviceTable(mesh, kk=_KK, cap=_CAP, acc=accs[-1])

    s1, s2 = _roundtrip(tmp_path_factory.mktemp("ck"), factory, img)
    s1.close()
    s2.close()
    assert accs[0].equal(accs[1])


@settings(max_examples=4, deadline=None)
@given(st.data())
def test_fuzz_device_topk_snapshot_roundtrip(tmp_path_factory, data):
    mesh = default_mesh(_N_DEV)
    img = _table_img(data.draw)
    accs = []

    def factory():
        accs.append(_CaptureAcc())
        return DeviceTopK(mesh, kk=_KK, cap=_CAP, k=4, acc=accs[-1])

    s1, s2 = _roundtrip(tmp_path_factory.mktemp("ck"), factory, img)
    s1.close()
    s2.close()
    assert accs[0].equal(accs[1])


@settings(max_examples=6, deadline=None)
@given(st.data())
def test_fuzz_device_postings_snapshot_roundtrip(tmp_path_factory, data):
    mesh = default_mesh(_N_DEV)
    width = _KK + 4
    m = data.draw(st.integers(0, _CAP))
    img = {
        "buf": data.draw(hnp.arrays(np.uint32, (_N_DEV, m, width),
                                    elements=st.integers(0, 2 ** 32 - 1))),
        "nrows": data.draw(hnp.arrays(np.int64, (_N_DEV,),
                                      elements=st.integers(0, m))),
        "cap": np.array(_CAP, dtype=np.int64),
    }
    sinks = []

    def factory():
        rows = []
        sinks.append(rows)
        return DevicePostings(mesh, width=width, cap=_CAP,
                              sink=lambda r, rows=rows: rows.append(
                                  np.array(r)))

    s1, s2 = _roundtrip(tmp_path_factory.mktemp("ck"), factory, img)
    s1.close()
    s2.close()
    assert len(sinks[0]) == len(sinks[1])
    assert all(np.array_equal(a, b) for a, b in zip(sinks[0], sinks[1]))


@settings(max_examples=6, deadline=None)
@given(hnp.arrays(np.uint64, (_N_DEV, 6),
                  elements=st.integers(0, 2 ** 64 - 1)))
def test_fuzz_device_histogram_snapshot_roundtrip(tmp_path_factory, state):
    mesh = default_mesh(_N_DEV)
    h1 = DeviceHistogram(mesh, slots=6)
    h1.restore_state({"hist": state})
    img = h1.checkpoint_state()
    store = CheckpointStore(str(tmp_path_factory.mktemp("ck")), "fuzz", {})
    store.save(img, {})
    _, arrays = store.load_latest()
    h2 = DeviceHistogram(mesh, slots=6)
    h2.restore_state(arrays)
    assert np.array_equal(h1.close(), h2.close())


@settings(max_examples=10, deadline=None)
@given(st.lists(st.tuples(st.integers(0, 2 ** 64 - 1),
                          st.integers(1, 2 ** 40)), max_size=30))
def test_fuzz_keycounts_snapshot_roundtrip(pairs):
    from dsi_tpu.device import KeyCounts

    kc = KeyCounts()
    for k, c in pairs:
        kc._counts[k] = kc._counts.get(k, 0) + c
    kc2 = KeyCounts()
    kc2.restore(kc.snapshot())
    assert kc2.finalize() == kc.finalize()


# ── delta-chain restore properties (ISSUE 8) ───────────────────────────


def _chain_run(words, dacc, save_shards, resume_shards, table_cap,
               tmpdir):
    """Random fold sequence → interleaved full/delta saves (cadence 1,
    small re-base window so fulls and deltas interleave) → restore at
    EVERY seq → byte-equal final output.  GC is disabled for the run so
    every restore point stays walkable; each seq is restored from a
    pruned copy of the store (manifests above it deleted — exactly the
    on-disk state a crash right after that save leaves, modulo
    retention)."""
    import shutil

    from dsi_tpu.parallel.streaming import wordcount_streaming

    mesh = default_mesh(4)
    line = (" ".join(words) + "\n").encode()
    # >= 4 steps at 1 KB/device chunks on the 4-dev mesh, whatever the
    # drawn vocabulary's line width — a chain needs several links.
    text = line * max(4, (16 << 10) // len(line) + 1)

    def run(ck=None, resume=False, shards=0):
        return wordcount_streaming(
            [text], mesh=mesh, n_reduce=10, chunk_bytes=1 << 10,
            u_cap=256, depth=2, device_accumulate=dacc, sync_every=2,
            mesh_shards=shards if dacc else 0, checkpoint_dir=ck,
            checkpoint_every=1, checkpoint_delta=True,
            checkpoint_async=True, resume=resume)

    base = run()
    assert base is not None
    ck = os.path.join(str(tmpdir), "ck")
    gc_orig = CheckpointStore._gc
    old_env = {k: os.environ.get(k) for k in
               ("DSI_STREAM_CKPT_REBASE", "DSI_DEVICE_TABLE_CAP")}
    os.environ["DSI_STREAM_CKPT_REBASE"] = "3"
    if table_cap:
        os.environ["DSI_DEVICE_TABLE_CAP"] = str(table_cap)
    try:
        CheckpointStore._gc = lambda self: None  # keep every seq
        assert run(ck=ck, shards=save_shards) == base
        seqs = sorted(
            int(m.group(1)) for n in os.listdir(ck)
            if (m := re.match(r"^manifest-(\d{6})\.json$", n)))
        assert len(seqs) >= 3
        kinds = set()
        for n in os.listdir(ck):
            kinds.add("delta" if n.startswith("delta-") else
                      "full" if n.startswith("state-") else None)
        assert {"full", "delta"} <= kinds  # saves really interleaved
        for s in seqs:
            trunc = os.path.join(str(tmpdir), f"at{s}")
            shutil.copytree(ck, trunc)
            for n in os.listdir(trunc):
                m = re.match(r"^(?:manifest|state|delta)-(\d{6})", n)
                if m and int(m.group(1)) > s:
                    os.remove(os.path.join(trunc, n))
            assert run(ck=trunc, resume=True,
                       shards=resume_shards) == base, \
                f"restore at seq {s} diverged"
    finally:
        CheckpointStore._gc = gc_orig
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@settings(max_examples=2, deadline=None)
@given(st.data())
def test_fuzz_delta_chain_restores_at_every_seq(tmp_path_factory, data):
    words = data.draw(st.lists(
        st.text(alphabet="abcdefghijklmnopqrstuvwxyz", min_size=1,
                max_size=8), min_size=3, max_size=40, unique=True))
    dacc = data.draw(st.booleans())
    _chain_run(words, dacc=dacc, save_shards=0, resume_shards=0,
               table_cap=0, tmpdir=tmp_path_factory.mktemp("chain"))


@settings(max_examples=1, deadline=None)
@given(st.data())
def test_fuzz_delta_chain_forced_widen_and_mesh_straddle(
        tmp_path_factory, data):
    """The hostile pair the ISSUE names: a forced device-table widen
    inside the chain window (tiny capacity rung), and a
    ``--mesh-shards`` degree change straddling the deltas (saved at
    degree 2, every restore at degree 0 — the drain-path re-entry)."""
    words = data.draw(st.lists(
        st.text(alphabet="abcdefghijklmnopqrstuvwxyz", min_size=1,
                max_size=8), min_size=20, max_size=60, unique=True))
    _chain_run(words, dacc=True, save_shards=2, resume_shards=0,
               table_cap=16, tmpdir=tmp_path_factory.mktemp("straddle"))


@settings(max_examples=40, deadline=None)
@given(st.lists(st.text(alphabet="abcdefghijklmnopqrstuvwxyzABC",
                        min_size=1, max_size=16),
                min_size=1, max_size=80),
       st.sampled_from([2, 3, 8]))
def test_fuzz_mesh_routing_partitions_exactly(words, n_shards):
    """Shard-routing invariant (ISSUE 7): for ANY key multiset, the
    on-device ``ihash(key) % n_shards`` route (ops/meshroute.py — the
    prologue of every mesh_fold_* program) partitions exactly — every
    key lands on exactly one in-range shard, duplicates agree, the
    union is the input — and matches the host ihash oracle from
    mr/worker.py byte-for-byte."""
    import functools

    import numpy as np

    from dsi_tpu.ops.meshroute import pack_host_rows, route_dest

    kk = 4  # the 16-byte word window; max_size above stays within it
    bwords = [w.encode("ascii") for w in words]
    keys, lens, oracle = pack_host_rows(bwords, n_shards, kk)
    valid = np.ones(len(bwords), dtype=bool)
    route = jax.jit(functools.partial(route_dest, n_shards=n_shards,
                                      park=n_shards))
    dest = np.asarray(route(keys, lens, valid))
    # Exact partition: every key on one in-range shard...
    assert ((dest >= 0) & (dest < n_shards)).all()
    # ...duplicates agree (ownership is a pure function of the key)...
    seen = {}
    for w, d in zip(bwords, dest.tolist()):
        assert seen.setdefault(w, d) == d
    # ...and device == host oracle (mr.worker ihash), byte-for-byte.
    assert dest.tolist() == oracle.tolist()
    for w, d in zip(words, dest.tolist()):
        assert d == ihash(w) % n_shards
    # Invalid rows park on the dump destination, never on a shard.
    none_valid = np.zeros(len(bwords), dtype=bool)
    parked = np.asarray(route(keys, lens, none_valid))
    assert (parked == n_shards).all()
