"""Serving-QoS tests (ISSUE 19): priority admission, load shedding,
rate limits, tail-driven eviction, and the packed grep lanes.

Two layers, the qos.py discipline:

* deterministic units — injected clocks, injected histograms, stubbed
  residents, monkeypatched RPC — no daemon scheduler, no sleeps, no
  wall-clock races;
* end-to-end integration on the in-process daemon — priority ordering
  observable in ``done_ts``, packed-grep byte parity vs the host
  oracle (literal, non-literal/hostpath, rung-widen, and evict/resume
  arms), the packing evidence in ``grep_packer.stats``;
* the ``slow``-marked soak — ``scripts/serve_soak.run_soak(1000)``,
  the acceptance bar's thousands-of-tenants churn.
"""

import json
import os
import sys
import tempfile

import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.serve import client, qos
from dsi_tpu.serve.daemon import ServeDaemon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def short_sock() -> str:
    # AF_UNIX paths cap at ~108 bytes; pytest tmp dirs can exceed it.
    return os.path.join(tempfile.mkdtemp(prefix="dsi-qos-"), "s.sock")


def grep_oracle_bytes(path: str, pattern: str) -> bytes:
    """grep.json ground truth: grep_host_oracle serialized exactly as
    ServeDaemon._write_grep_result spells it."""
    from dsi_tpu.parallel.grepstream import grep_host_oracle

    with open(path, "rb") as f:
        r = grep_host_oracle([f.read()], pattern)
    return json.dumps(
        {"lines": r.lines, "matched": r.matched,
         "occurrences": r.occurrences, "hist": list(r.hist),
         "topk": [list(t) for t in r.topk]},
        sort_keys=True).encode("utf-8")


def grep_corpus(path: str, pat: str, n_lines: int = 400,
                line_fill: str = " fill") -> str:
    with open(path, "w") as f:
        for j in range(n_lines):
            f.write((pat + " ") * (j % 4) + f"x{j % 13}{line_fill}\n")
    return path


# ── units: the policy objects, injected clocks ──


def test_priority_queue_strict_order_and_lanes():
    q = qos.PriorityQueue()
    q.push("b1", 2)
    q.push("d1", 1)
    q.push("a1", 0)
    q.push("d2", 1)
    q.push("a2", 0)
    assert len(q) == 5 and "d2" in q
    assert q.depths() == (2, 2, 1)
    assert list(q) == ["a1", "a2", "d1", "d2", "b1"]
    # push_front re-queues at the head of the job's OWN lane only —
    # a parked batch job must not cut ahead of the interactive lane.
    q.push_front("b0", 2)
    assert list(q) == ["a1", "a2", "d1", "d2", "b0", "b1"]
    assert [q.pop() for _ in range(6)] == \
        ["a1", "a2", "d1", "d2", "b0", "b1"]
    assert q.pop() is None
    q.push("x", 7)       # out-of-range priorities clamp, never KeyError
    q.push("y", -3)
    assert q.depths() == (1, 0, 1)
    assert q.remove("x") and not q.remove("x")


def test_token_bucket_injected_clock():
    now = [100.0]
    b = qos.TokenBucket(rate=2.0, burst=2, clock=lambda: now[0])
    assert b.take() == 0.0 and b.take() == 0.0   # burst admits
    hint = b.take()                              # empty: a real hint
    assert hint == pytest.approx(0.5, abs=0.01)  # 1 token / 2 per s
    now[0] += 0.5                                # one token accrues
    assert b.take() == 0.0
    assert b.take() > 0.0
    shut = qos.TokenBucket(rate=0.0, burst=1, clock=lambda: now[0])
    assert shut.take() == 0.0                    # the burst token
    assert shut.take() == 60.0                   # rate 0: long hint
    rep = qos.backpressure_reply("full", hint)
    assert rep["error_type"] == "backpressure" and rep["retryable"]
    assert rep["retry_after_s"] == pytest.approx(hint, abs=0.001)


def test_submit_shed_at_queue_bound(tmp_path):
    """max_queue=1 on a never-started daemon: the second submission is
    SHED with the typed reply and no journal entry."""
    corpus = grep_corpus(str(tmp_path / "c.txt"), "abc")
    d = ServeDaemon(str(tmp_path / "spool"), socket_path=short_sock(),
                    warm=False, max_queue=1)
    try:
        ok = d._rpc_submit({"tenant": "t0", "app": "wc",
                            "files": [corpus]})
        assert "job_id" in ok
        shed = d._rpc_submit({"tenant": "t1", "app": "wc",
                              "files": [corpus]})
        assert shed["error_type"] == "backpressure"
        assert shed["retry_after_s"] >= 0.2
        assert d._qos["shed"] == 1
        # The shed submission left NO spool state (zero-lost counts
        # accepted acks only).
        assert len([f for f in os.listdir(d.jobs_dir)
                    if f.endswith(".json")]) == 1
    finally:
        d._rpc.close()


def test_submit_rate_limit_injected_clock(tmp_path):
    corpus = grep_corpus(str(tmp_path / "c.txt"), "abc")
    now = [50.0]
    d = ServeDaemon(str(tmp_path / "spool"), socket_path=short_sock(),
                    warm=False, rate_limit=1.0, rate_burst=1,
                    clock=lambda: now[0])
    try:
        sub = {"tenant": "rl", "app": "wc", "files": [corpus]}
        assert "job_id" in d._rpc_submit(sub)
        rep = d._rpc_submit(sub)
        assert rep["error_type"] == "backpressure"
        assert 0.0 < rep["retry_after_s"] <= 1.0
        assert d._qos["rate_limited"] == 1
        # A different tenant has its own bucket.
        assert "job_id" in d._rpc_submit({"tenant": "other",
                                          "app": "wc",
                                          "files": [corpus]})
        now[0] += 1.0                        # one token accrues
        assert "job_id" in d._rpc_submit(sub)
    finally:
        d._rpc.close()


def test_client_honors_retry_after_hint(monkeypatch):
    """ServeBusy carries the daemon's hint; submit(retries=) sleeps
    hint x jitter (bounded) and retries; exhaustion re-raises."""
    busy = (True, qos.backpressure_reply("queue full", 1.0))
    replies = [busy, busy, (True, {"job_id": "j-000001"})]
    calls = []

    def fake_call(sock, method, args, timeout=30.0):
        calls.append(method)
        return replies[len(calls) - 1]

    slept = []
    monkeypatch.setattr(client, "call", fake_call)
    rep = client.submit("/nowhere.sock", "t", [__file__], retries=2,
                        sleep=slept.append, rng=lambda: 0.25)
    assert rep["job_id"] == "j-000001" and len(calls) == 3
    # jitter = 0.5 + rng() = 0.75, hint = 1.0 → both sleeps 0.75s.
    assert slept == [pytest.approx(0.75), pytest.approx(0.75)]
    calls.clear()
    replies[:] = [busy, busy]
    with pytest.raises(client.ServeBusy) as ei:
        client.submit("/nowhere.sock", "t", [__file__], retries=1,
                      sleep=slept.append, rng=lambda: 0.0)
    assert ei.value.retry_after_s == pytest.approx(1.0)
    assert len(calls) == 2                   # retries=1 → 2 attempts


class _StubLane:
    def __init__(self, steps: int):
        self.steps_since_resume = steps
        self.suspended = False

    def suspend(self):
        self.suspended = True


def _stub_resident(d: ServeDaemon, jid: str, tenant: str,
                   steps: int) -> _StubLane:
    lane = _StubLane(steps)
    d._jobs[jid] = {"job_id": jid, "tenant": tenant, "app": "wc",
                    "files": [], "n_reduce": 10,
                    "out_dir": os.path.join(d.out_dir, jid),
                    "pattern": None, "priority": 1, "state": "running",
                    "submitted_ts": 0.0, "done_ts": None,
                    "error": None, "stats": {}}
    d._resident[jid] = {"kind": "wc", "lane": lane}
    return lane


def test_evict_one_picks_worst_p99_tail(tmp_path):
    """Tail-driven eviction: among residents past min residency, the
    victim is the tenant whose p99 packed-step wall is worst — not the
    one furthest past quota."""
    d = ServeDaemon(str(tmp_path / "spool"), socket_path=short_sock(),
                    warm=False, quota_steps=4, evict_min_samples=3)
    try:
        _stub_resident(d, "fast-000001", "fast", steps=9)
        slow = _stub_resident(d, "slow-000002", "slow", steps=5)
        for _ in range(3):
            d._hist.record("fast", 0.001)
            d._hist.record("slow", 0.5)      # the pack-hurting tail
        with d._wake:
            d._evict_one()
        assert slow.suspended
        assert "slow-000002" not in d._resident
        assert d._jobs["slow-000002"]["state"] == "parked"
        assert "slow-000002" in d._queue
        assert d._qos["evict_p99"] == 1 and d._qos["evict_quota"] == 0
        assert d._tenants["slow"]["evictions"] == 1
    finally:
        d._rpc.close()


def test_evict_one_quota_fallback_without_tails(tmp_path):
    """No resident has a meaningful histogram yet → the original
    furthest-past-quota rule decides, counted separately."""
    d = ServeDaemon(str(tmp_path / "spool"), socket_path=short_sock(),
                    warm=False, quota_steps=2, evict_min_samples=3)
    try:
        _stub_resident(d, "a-000001", "a", steps=3)
        far = _stub_resident(d, "b-000002", "b", steps=7)
        with d._wake:
            d._evict_one()
        assert far.suspended and "b-000002" not in d._resident
        assert d._qos["evict_quota"] == 1 and d._qos["evict_p99"] == 0
        # Fresh residents under quota are never victims.
        d._resident.clear()
        _stub_resident(d, "c-000003", "c", steps=1)
        with d._wake:
            d._evict_one()
        assert "c-000003" in d._resident
    finally:
        d._rpc.close()


def test_metrics_and_statusz_bounded_by_tenant_cap(tmp_path):
    """metrics_tenants caps the per-tenant series and the statusz
    table regardless of how many tenants exist; worst-p99 tenants win
    the slots."""
    d = ServeDaemon(str(tmp_path / "spool"), socket_path=short_sock(),
                    warm=False, metrics_tenants=2)
    try:
        for i in range(5):
            d._tenant(f"m{i}")
        d._hist.record("m3", 2.0)            # the tail tenants the
        d._hist.record("m4", 1.0)            # cap must keep visible
        metrics = d._metrics_section()
        steps_lines = [l for l in metrics.splitlines()
                       if l.startswith("dsi_serve_tenant_steps{")]
        assert len(steps_lines) == 2
        assert any('tenant="m3"' in l for l in steps_lines)
        assert any('tenant="m4"' in l for l in steps_lines)
        # Every emitted series name is registry-declared (the schema
        # contract: SERVE_SERIES is the closed set).
        from dsi_tpu.obs.registry import SERVE_SERIES

        for line in metrics.splitlines():
            if line.startswith("dsi_serve"):
                name = line.split("{")[0].split(" ")[0]
                assert name in SERVE_SERIES, line
        st = d._statusz_section()
        assert "3 more tenants" in st
    finally:
        d._rpc.close()


# ── integration: the daemon end to end ──


def test_priority_admission_end_to_end(tmp_path):
    """max_resident=1 serializes the run order: a priority-0 job
    submitted LAST still finishes before the priority-2 jobs queued
    ahead of it."""
    spool = str(tmp_path / "spool")
    subs = []
    for i in range(2):
        p = grep_corpus(str(tmp_path / f"low{i}.txt"), "low", 200)
        subs.append(("low%d" % i, p, 2))
    p = grep_corpus(str(tmp_path / "hi.txt"), "hi", 200)
    subs.append(("hi", p, 0))
    d = ServeDaemon(spool, socket_path=short_sock(), warm=False,
                    max_resident=1)
    reps = {t: d._rpc_submit({"tenant": t, "app": "wc", "files": [f],
                              "priority": pr})
            for t, f, pr in subs}
    assert all("job_id" in r for r in reps.values())
    d.start()
    try:
        client.wait_ready(d.socket_path, timeout=120)
        final = client.wait(d.socket_path,
                            [r["job_id"] for r in reps.values()],
                            timeout=180)
        assert all(j["state"] == "done" for j in final.values()), final
        done = {j["tenant"]: j["done_ts"] for j in final.values()}
        assert done["hi"] <= min(done["low0"], done["low1"])
    finally:
        d.close()


def test_packed_grep_parity_and_hostpath(tmp_path):
    """Six literal grep tenants across two pattern lengths pack into
    shared waves (the packing evidence in grep_packer.stats); a
    seventh non-literal tenant rides the host path; every tenant's
    grep.json byte-compares equal to the host oracle."""
    spool = str(tmp_path / "spool")
    pats = ["abc", "dog", "cat", "whale", "zebra", "quail"]
    jobs = []
    for i, pat in enumerate(pats):
        p = grep_corpus(str(tmp_path / f"g{i}.txt"), pat, 300)
        jobs.append((f"g{i}", p, pat))
    p = grep_corpus(str(tmp_path / "re.txt"), "qaz", 300)
    jobs.append(("re", p, "q.z"))        # regex meta → host path
    p = str(tmp_path / "long.txt")
    with open(p, "w") as f:              # a line wider than one row:
        f.write("abc ok\n" + "abc " * 2000 + "\nabc tail\n")
    jobs.append(("longline", p, "abc"))  # mid-stream host fallback
    d = ServeDaemon(spool, socket_path=short_sock(), warm=False,
                    chunk_bytes=1 << 12, max_resident=8)
    reps = {t: d._rpc_submit({"tenant": t, "app": "grep",
                              "files": [f], "pattern": pat})
            for t, f, pat in jobs}
    assert all("job_id" in r for r in reps.values())
    d.start()
    try:
        client.wait_ready(d.socket_path, timeout=120)
        final = client.wait(d.socket_path,
                            [r["job_id"] for r in reps.values()],
                            timeout=180)
        assert all(j["state"] == "done" for j in final.values()), final
        for t, f, pat in jobs:
            with open(os.path.join(reps[t]["out_dir"], "grep.json"),
                      "rb") as fh:
                assert fh.read() == grep_oracle_bytes(f, pat), t
        st = d.grep_packer.stats
        assert st["packed_rows"] >= st["packed_steps"] >= 1
        assert st["max_tenants_per_step"] >= 2
        assert st["host_fallbacks"] >= 1     # the over-wide line
        tenants = client.status(d.socket_path)["tenants"]
        assert tenants["re"]["hostpath"] == 1        # born host path
        assert tenants["longline"]["hostpath"] == 1  # mid-stream flip
        metrics = d._metrics_section()
        assert "dsi_serve_grep_packed_steps" in metrics
    finally:
        d.close()


def test_grep_rung_widen_stays_exact(tmp_path):
    """A tenant whose tiny lines overflow rung 0's line cap forces the
    clean-prefix requeue + per-tenant widen — and only that tenant's
    rung moves, with byte parity intact."""
    spool = str(tmp_path / "spool")
    tiny = str(tmp_path / "tiny.txt")
    with open(tiny, "w") as f:
        for j in range(2000):
            f.write("ab\n" if j % 3 else "a\n")   # >128 lines / 1KB row
    wide = grep_corpus(str(tmp_path / "wide.txt"), "ab", 200,
                       line_fill=" " + "f" * 40)
    d = ServeDaemon(spool, socket_path=short_sock(), warm=False,
                    chunk_bytes=1 << 10, max_resident=4)
    reps = {t: d._rpc_submit({"tenant": t, "app": "grep",
                              "files": [f], "pattern": "ab"})
            for t, f in (("tiny", tiny), ("wide", wide))}
    d.start()
    try:
        client.wait_ready(d.socket_path, timeout=120)
        final = client.wait(d.socket_path,
                            [r["job_id"] for r in reps.values()],
                            timeout=180)
        assert all(j["state"] == "done" for j in final.values()), final
        for t, f in (("tiny", tiny), ("wide", wide)):
            with open(os.path.join(reps[t]["out_dir"], "grep.json"),
                      "rb") as fh:
                assert fh.read() == grep_oracle_bytes(f, "ab"), t
        st = d.grep_packer.stats
        assert st["rung_widens"] >= 1 and st["replays"] >= 1
        # The widen is visible per job: the tiny tenant retired on a
        # higher rung.
        assert final[reps["tiny"]["job_id"]]["stats"]["rung"] >= 1
        assert final[reps["wide"]["job_id"]]["stats"]["rung"] == 0
    finally:
        d.close()


def test_grep_evict_resume_parity(tmp_path):
    """Grep lanes park on their checkpoint chains and resume exact:
    max_resident=1 + a 1-step quota over two multi-row tenants forces
    evict → park → resume cycles through the PACKED grep path."""
    spool = str(tmp_path / "spool")
    jobs = []
    for i in range(2):
        p = grep_corpus(str(tmp_path / f"e{i}.txt"), f"ev{i}", 600,
                        line_fill=" pad" * 4)
        jobs.append((f"ge{i}", p, f"ev{i}"))
    d = ServeDaemon(spool, socket_path=short_sock(), warm=False,
                    chunk_bytes=1 << 10, max_resident=1, quota_steps=1,
                    checkpoint_every=1)
    reps = {t: d._rpc_submit({"tenant": t, "app": "grep",
                              "files": [f], "pattern": pat})
            for t, f, pat in jobs}
    d.start()
    try:
        client.wait_ready(d.socket_path, timeout=120)
        final = client.wait(d.socket_path,
                            [r["job_id"] for r in reps.values()],
                            timeout=240)
        assert all(j["state"] == "done" for j in final.values()), final
        for t, f, pat in jobs:
            with open(os.path.join(reps[t]["out_dir"], "grep.json"),
                      "rb") as fh:
                assert fh.read() == grep_oracle_bytes(f, pat), t
        tenants = client.status(d.socket_path)["tenants"]
        assert sum(s["evictions"] for s in tenants.values()) >= 1
        assert sum(s["resumes"] for s in tenants.values()) >= 1
    finally:
        d.close()


@pytest.mark.slow
def test_soak_thousand_tenants():
    """The acceptance bar: 1000 mixed tenants of sustained
    submit/shed/evict/resume churn — zero lost jobs, shedding engaged,
    per-tenant byte parity, bounded dsi_serve_* series."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import serve_soak
    finally:
        sys.path.pop(0)
    summary = serve_soak.run_soak(1000)
    assert summary["parity"] and summary["shed"] >= 1
    assert summary["evictions"] >= 1 and summary["resumes"] >= 1
