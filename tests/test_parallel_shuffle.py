"""Multi-device SPMD shuffle: parity of the all_to_all data plane.

Runs the full map + all_to_all + reduce program on the virtual 8-device CPU
mesh (conftest.py) and checks it against (a) collections.Counter ground truth
and (b) the host app's partitioner (`ihash % n_reduce`, mr/worker.go:33-37,76),
i.e. the same differential-oracle discipline as test-mr.sh:52-53.
"""

import collections
import re

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.mr.worker import ihash
from dsi_tpu.parallel.shuffle import (
    default_mesh,
    mapreduce_step,
    shard_text,
    wordcount_sharded,
    write_partitioned_output,
)

WORDS = re.compile(r"[A-Za-z]+")


def make_text(n_bytes: int, seed: int = 7) -> bytes:
    rng = np.random.default_rng(seed)
    vocab = [b"alpha", b"Bet", b"gamma", b"d", b"epsilonlongword", b"Zz",
             b"supercalifragilistic", b"mid"]
    parts = []
    size = 0
    while size < n_bytes:
        w = vocab[int(rng.integers(len(vocab)))]
        sep = b" " if rng.random() < 0.8 else b"\n"
        parts.append(w + sep)
        size += len(w) + 1
    return b"".join(parts)[:n_bytes]


def truth(data: bytes):
    return collections.Counter(WORDS.findall(data.decode("ascii")))


def test_shard_text_no_token_splits():
    data = make_text(5000)
    chunks, size = shard_text(data, 8)
    merged = collections.Counter()
    for row in chunks:
        merged.update(WORDS.findall(row.tobytes().decode("ascii", "ignore")))
    assert merged == truth(data)


def test_sharded_wordcount_matches_counter():
    data = make_text(20000)
    mesh = default_mesh(8)
    res = wordcount_sharded(data, mesh=mesh, n_reduce=10, max_word_len=16,
                            u_cap=256)
    assert res is not None
    want = truth(data)
    assert {w: c for w, (c, _) in res.items()} == dict(want)
    for w, (_, r) in res.items():
        assert r == ihash(w) % 10  # bit-exact reference partitioner


def test_sharded_wordcount_word_overflow_retries():
    # 20-byte word forces the 16-byte kernel to retry at 64.
    data = (b"abcdefghijklmnopqrst " * 50) + b"tail word"
    res = wordcount_sharded(data, mesh=default_mesh(8), max_word_len=16,
                            u_cap=256)
    assert res is not None
    assert res["abcdefghijklmnopqrst"][0] == 50


def test_token_overflow_retries_exact_bound():
    # single-letter tokens at maximum density: n_tokens == n//2, overflowing
    # the compact frac=4 buffer and forcing the exact n//2+1 retry
    data = b"a b c d e f g h " * 200
    res = wordcount_sharded(data, mesh=default_mesh(8), u_cap=256)
    assert res is not None
    assert {w: c for w, (c, _) in res.items()} == dict(truth(data))

    from dsi_tpu.ops.wordcount import count_words_host_result
    single = count_words_host_result(data)
    assert {w: (c,) for w, (c, _) in single.items()} == \
        {w: (c,) for w, c in truth(data).items()}


def test_sharded_wordcount_non_ascii_falls_back():
    data = "héllo world".encode("utf-8")
    assert wordcount_sharded(data, mesh=default_mesh(8)) is None


def test_partition_ownership():
    """Each device's output rows carry only partitions it owns (r % D == d)."""
    data = make_text(8000)
    mesh = default_mesh(8)
    chunks_np, _ = shard_text(data, 8)
    keys, lens, cnts, parts, scal = mapreduce_step(
        jax.numpy.asarray(chunks_np), n_dev=8, n_reduce=10, max_word_len=32,
        u_cap=256, mesh=mesh)
    scal = np.asarray(scal)
    parts = np.asarray(parts)
    for d in range(8):
        nu = int(scal[d, 0])
        assert (parts[d, :nu] % 8 == d).all()


def test_write_partitioned_output(tmp_path):
    data = make_text(4000)
    res = wordcount_sharded(data, mesh=default_mesh(8), u_cap=256)
    paths = write_partitioned_output(res, 10, str(tmp_path))
    assert len(paths) == 10
    merged = {}
    for p in paths:
        with open(p) as f:
            for line in f:
                w, c = line.split()
                merged[w] = int(c)
    assert merged == dict(truth(data))
