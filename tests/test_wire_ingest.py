"""Compressed wire + parallel ingest (ISSUE 13).

Three layers under test:

* **`utils/ioread.py`** — the parallel mmap reader pool: the yielded
  byte stream must be BYTE-IDENTICAL to the serial reader at any
  reader count/block size (that identity is what keeps checkpoint
  cursors exact), the readahead stats must be sane, and abandoning the
  iterator mid-stream must tear the pool down cleanly.
* **`ops/wirecodec.py`** — both codecs round-trip bit-exactly: the
  varint streams, the shuffle-row dictionary codec (including the
  non-trimmable-key fallback), and the chunk codec in every mode
  (nibble rungs, 7-bit ASCII, raw refusal), with the compiled jax
  decode prologue equal to the numpy oracle.
* **engine integration** — `wordcount_streaming` with `wire_upload`
  on vs off is bit-identical across depth x dacc x mesh, reading
  through the reader pool is bit-identical to inline reads (with the
  ingest keys folded into `pipeline_stats`), and the compressed
  checkpoint store restores chains written in any compress mode.
"""

import os

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.ckpt import CheckpointStore
from dsi_tpu.ops import wirecodec as wc
from dsi_tpu.parallel.grepstream import grep_streaming
from dsi_tpu.parallel.shuffle import default_mesh
from dsi_tpu.parallel.streaming import wordcount_streaming
from dsi_tpu.utils import ioread


def _mesh():
    return default_mesh(4)


def _letters(i: int) -> str:
    return "".join(chr(97 + (i // 26 ** j) % 26) for j in range(3))


WC_WORDS = [_letters(i) for i in range(120)]
WC_TEXT = ((" ".join(WC_WORDS) + "\n") * 80).encode()  # ~38 KB, ~10 steps
WC_CHUNK = 1 << 10


# ── utils/ioread.py ────────────────────────────────────────────────────


def _write_files(tmp_path, sizes, seed=0):
    rng = np.random.default_rng(seed)
    paths = []
    for i, size in enumerate(sizes):
        p = tmp_path / f"f{i}.txt"
        p.write_bytes(bytes(rng.integers(32, 127, size, dtype=np.uint8)))
        paths.append(str(p))
    return paths


@pytest.mark.parametrize("readers", [1, 3, 8])
def test_parallel_blocks_byte_identical_to_serial(tmp_path, readers):
    paths = _write_files(tmp_path, [0, 17, 5000, 123457, 0, 64])
    want = b"".join(ioread.serial_blocks(paths, block_bytes=1000))
    pool = ioread.ParallelBlocks(paths, block_bytes=997, readers=readers)
    got = b"".join(pool)
    assert got == want
    st = pool.ingest_stats()
    assert st["ingest_readers"] == readers
    assert st["ingest_blocks"] > 100
    assert 0.0 <= st["readahead_hit_pct"] <= 100.0
    assert st["ingest_wait_s"] >= 0.0


def test_parallel_blocks_abandoned_mid_stream_tears_down(tmp_path):
    paths = _write_files(tmp_path, [50000, 50000])
    pool = ioread.ParallelBlocks(paths, block_bytes=512, readers=2)
    it = iter(pool)
    next(it)
    next(it)
    it.close()  # the generator's finally runs pool.close()
    for t in pool._threads:
        t.join(timeout=5.0)
        assert not t.is_alive()


def test_parallel_blocks_second_pass_raises_not_hangs(tmp_path):
    paths = _write_files(tmp_path, [5000])
    pool = ioread.ParallelBlocks(paths, block_bytes=512, readers=2)
    assert b"".join(pool)  # first pass exhausts and closes
    with pytest.raises(RuntimeError, match="single-pass"):
        next(iter(pool))


def test_open_blocks_resolves_reader_knob(tmp_path, monkeypatch):
    paths = _write_files(tmp_path, [100])
    monkeypatch.delenv("DSI_INGEST_READERS", raising=False)
    assert not isinstance(ioread.open_blocks(paths),
                          ioread.ParallelBlocks)
    monkeypatch.setenv("DSI_INGEST_READERS", "3")
    pool = ioread.open_blocks(paths)
    assert isinstance(pool, ioread.ParallelBlocks)
    assert pool.readers == 3
    # Explicit argument wins over the env.
    assert ioread.open_blocks(paths, readers=2).readers == 2


def test_parallel_blocks_missing_file_raises(tmp_path):
    paths = _write_files(tmp_path, [4096])
    pool = ioread.ParallelBlocks(paths, block_bytes=512, readers=2)
    os.remove(paths[0])
    # The plan was built at construction; the read itself must surface
    # the error on the CONSUMER thread, not hang the pool.
    with pytest.raises(OSError):
        list(pool)


# ── wirecodec: varints + shuffle-row codec ─────────────────────────────


def test_varint_round_trip_boundaries():
    vals = [0, 1, 127, 128, 255, 16383, 16384, 2 ** 32 - 1, 2 ** 40]
    enc = wc.varint_encode(vals)
    dec, off = wc.varint_decode(enc + b"trailing", len(vals))
    assert list(dec) == vals
    assert off == len(enc)
    assert wc.varint_encode([]) == b""
    with pytest.raises(ValueError):
        wc.varint_decode(b"\x80\x80", 1)  # truncated continuation


def _fake_packed_table(n_dev=4, mp=64, kk=4, nus=(50, 3, 0, 64)):
    rows = np.zeros((n_dev, mp, kk + 3), np.uint32)
    words = [b"the", b"a", b"wordcount", b"zz", b"longestword1"]
    for d in range(n_dev):
        for r in range(nus[d]):
            w = words[(d + r) % len(words)] + str(r % 7).encode()
            kb = np.zeros(kk * 4, np.uint8)
            kb[:len(w)] = np.frombuffer(w, np.uint8)
            rows[d, r, :kk] = kb.view(">u4")
            rows[d, r, kk] = len(w)
            rows[d, r, kk + 1] = r + 1
            rows[d, r, kk + 2] = r % 10
    return rows, np.asarray(nus, np.int64)


def test_pack_rows_round_trip_and_ratio():
    rows, nus = _fake_packed_table()
    blob = wc.pack_rows(rows, nus)
    rows2, nus2 = wc.unpack_rows(blob)
    assert np.array_equal(rows2, rows)
    assert np.array_equal(nus2, nus)
    # The acceptance bar: dictionary+varint beats raw rows well past
    # 1.5x on word-count-shaped payloads.
    assert wc.rows_raw_bytes(nus, 4) / len(blob) > 1.5


def test_pack_rows_empty_and_untrimmable_fallback():
    rows = np.zeros((2, 8, 7), np.uint32)
    blob = wc.pack_rows(rows, [0, 0])
    rows2, nus2 = wc.unpack_rows(blob)
    assert rows2.shape == (2, 8, 7) and not nus2.any()
    # A key whose lanes carry bytes BEYOND its recorded length defeats
    # trailing-zero trimming: the codec must fall back to full-width
    # dictionary entries, still bit-exact.
    rows, nus = _fake_packed_table(nus=(4, 0, 0, 0))
    kb = np.full(16, 0xAB, np.uint8)
    rows[0, 0, :4] = kb.view(">u4")
    rows[0, 0, 4] = 3  # claims 3 bytes; lanes hold 16 nonzero
    blob = wc.pack_rows(rows, nus)
    rows2, nus2 = wc.unpack_rows(blob)
    for d in range(4):
        assert np.array_equal(rows2[d, :int(nus[d])], rows[d, :int(nus[d])])


# ── wirecodec: chunk codec, every mode ─────────────────────────────────


def test_chunk_codec_nibble_mode_round_trip():
    text = (b"the the the and and of of to a in is it " * 2000)
    n = 1 << 13
    batch = np.zeros((2, n), np.uint8)
    batch[0] = np.frombuffer(text[:n], np.uint8)
    batch[1, :50] = np.frombuffer(text[:50], np.uint8)
    mode, packed, cap = wc.encode_chunk(batch)
    assert mode == "nib" and cap in wc.lit_caps(n)
    assert packed.nbytes < batch.nbytes  # the wire actually shrinks
    assert np.array_equal(wc.decode_chunk_host(mode, packed, n), batch)
    out = np.asarray(wc.decode_chunk_device(
        jax.device_put(packed), n=n, lit_cap=cap, mode=mode))
    assert np.array_equal(out, batch)


def test_chunk_codec_7bit_mode_round_trip():
    # Uniform letter usage defeats the 15-entry dictionary; all-ASCII
    # input must fall to the guaranteed 8/7 mode.
    rng = np.random.default_rng(7)
    batch = rng.integers(0, 128, (3, 1 << 12), dtype=np.uint8)
    mode, packed, cap = wc.encode_chunk(batch)
    assert mode == "b7" and cap == 0
    assert packed.nbytes * 8 == batch.nbytes * 7
    assert np.array_equal(wc.decode_chunk_host(mode, packed, 1 << 12),
                          batch)
    out = np.asarray(wc.decode_chunk_device(
        jax.device_put(packed), n=1 << 12, lit_cap=0, mode=mode))
    assert np.array_equal(out, batch)


def test_chunk_codec_refuses_incompressible_and_odd_shapes():
    rng = np.random.default_rng(1)
    assert wc.encode_chunk(
        rng.integers(0, 256, (2, 1 << 10), dtype=np.uint8)) is None
    assert wc.encode_chunk(np.zeros((2, 12), np.uint8)) is None  # n%8


# ── engine integration ─────────────────────────────────────────────────


def _wc_run(blocks, stats=None, **kw):
    return wordcount_streaming(blocks, mesh=_mesh(), n_reduce=10,
                               chunk_bytes=WC_CHUNK, u_cap=256,
                               pipeline_stats=stats, **kw)


@pytest.mark.parametrize("dacc,depth,shards", [
    (False, 1, None), (False, 2, None), (True, 2, None), (True, 2, 4),
])
def test_wire_upload_bit_identical(dacc, depth, shards):
    base = _wc_run([WC_TEXT])
    stats: dict = {}
    got = _wc_run([WC_TEXT], stats=stats, wire_upload=True, depth=depth,
                  device_accumulate=dacc, mesh_shards=shards)
    assert got == base
    assert stats["wire_upload"] is True
    assert stats["wire_steps"] + stats["wire_raw_steps"] == stats["steps"]
    assert stats["wire_steps"] > 0          # this text compresses
    assert stats["wire_ratio"] > 1.0
    assert stats["decode_s"] >= 0.0


def test_reader_pool_bit_identical_with_stats(tmp_path):
    paths = []
    half = len(WC_TEXT) // 2
    for i, piece in enumerate((WC_TEXT[:half], WC_TEXT[half:])):
        p = tmp_path / f"c{i}.txt"
        p.write_bytes(piece)
        paths.append(str(p))
    want = _wc_run([b"".join(ioread.serial_blocks(paths))])
    stats: dict = {}
    got = _wc_run(ioread.ParallelBlocks(paths, block_bytes=4096,
                                        readers=3), stats=stats)
    assert got == want
    assert stats["ingest_readers"] == 3
    assert stats["ingest_blocks"] > 0
    assert "readahead_hit_pct" in stats and "ingest_wait_s" in stats


def test_reader_pool_grep_bit_identical(tmp_path):
    lines = []
    for i in range(2000):
        lines.append(b"ab " * (i % 5) + b"line" + str(i).encode())
    text = b"\n".join(lines) + b"\n"
    p = tmp_path / "g.txt"
    p.write_bytes(text)
    want = grep_streaming([text], "ab", mesh=_mesh(), chunk_bytes=1 << 11)
    stats: dict = {}
    got = grep_streaming(
        ioread.ParallelBlocks([str(p)], block_bytes=1500, readers=2),
        "ab", mesh=_mesh(), chunk_bytes=1 << 11, pipeline_stats=stats)
    assert got == want
    assert stats["ingest_readers"] == 2


# ── compressed checkpoint store ────────────────────────────────────────


def _arrays():
    rng = np.random.default_rng(3)
    # Repetitive packed-table-shaped payload: zlib must bite hard.
    rows = np.repeat(rng.integers(0, 1000, (1, 64, 7)), 16,
                     axis=0).astype(np.uint32)
    return {"rows": rows, "nus": np.full(16, 64, np.int64)}


def test_store_compresses_deltas_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("DSI_STREAM_CKPT_COMPRESS", raising=False)
    st = CheckpointStore(str(tmp_path / "ck"), "wordcount", {"j": 1})
    assert st.compress == "deltas"
    arrays = _arrays()
    st.save(arrays, {"cursor": 0})
    full_bytes = st.last_payload_bytes
    assert st.last_compress_s == 0.0  # fulls stay raw under "deltas"
    st.save_delta(arrays, {"cursor": 1})
    # Identical arrays: the compressed delta must be >= 2x smaller than
    # the raw full image of the same payload — the acceptance bar.
    assert st.last_payload_bytes * 2 <= full_bytes
    assert st.last_payload_raw_bytes == sum(v.nbytes
                                            for v in arrays.values())
    assert st.last_compress_s >= 0.0
    # Chain restore reads the mixed raw/zlib chain transparently.
    meta, base, deltas = st.load_latest_chain()
    assert int(deltas[-1][0]["cursor"]) == 1
    assert np.array_equal(base["rows"], arrays["rows"])
    assert np.array_equal(deltas[0][1]["rows"], arrays["rows"])


@pytest.mark.parametrize("mode,full_zipped,delta_zipped", [
    ("off", False, False), ("deltas", False, True), ("all", True, True),
])
def test_store_compress_modes(tmp_path, mode, full_zipped, delta_zipped):
    st = CheckpointStore(str(tmp_path / mode), "wordcount", {"j": 1},
                         compress=mode)
    arrays = _arrays()
    st.save(arrays, {})
    full = st.last_payload_bytes
    st.save_delta(arrays, {})
    delta = st.last_payload_bytes
    raw = st.last_payload_raw_bytes
    # Zipped payloads of this repetitive table are far below raw;
    # unzipped ones are raw + npz framing overhead.
    assert (full < raw) == full_zipped
    assert (delta < raw) == delta_zipped
    assert st.load_latest_chain() is not None


def test_wc_crash_resume_with_compressed_deltas(monkeypatch, tmp_path):
    """End-to-end: wire upload + compressed async delta chain + a
    mid-fold fault, resumed bit-identically (the CI smoke's in-process
    twin)."""
    from dsi_tpu.ckpt import FaultInjected, reset_faults

    base = _wc_run([WC_TEXT])
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("DSI_STREAM_CKPT_COMPRESS", "deltas")
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    monkeypatch.setenv("DSI_FAULT_POINT", "mid-fold")
    monkeypatch.setenv("DSI_FAULT_STEP", "4")
    reset_faults()
    with pytest.raises(FaultInjected):
        _wc_run([WC_TEXT], checkpoint_dir=ck, checkpoint_every=1,
                checkpoint_async=True, checkpoint_delta=True,
                wire_upload=True)
    for k in ("DSI_FAULT_MODE", "DSI_FAULT_POINT", "DSI_FAULT_STEP"):
        monkeypatch.delenv(k, raising=False)
    reset_faults()
    stats: dict = {}
    got = _wc_run([WC_TEXT], stats=stats, checkpoint_dir=ck,
                  checkpoint_every=1, checkpoint_async=True,
                  checkpoint_delta=True, wire_upload=True, resume=True)
    assert got == base
    assert stats["resume_cursor"] > 0
    assert stats["ckpt_compress"] == "deltas"
