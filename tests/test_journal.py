"""Coordinator checkpoint/resume: the capability the reference lacks
(in-memory-only coordinator state, mr/coordinator.go:17,21; SURVEY.md §5)."""

import os
import threading
import time

import pytest

from dsi_tpu.config import JobConfig
from dsi_tpu.mr.coordinator import Coordinator, make_coordinator
from dsi_tpu.mr.journal import Journal
from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.worker import worker_loop
from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output


def _cfg(tmp_path, **kw):
    return JobConfig(workdir=str(tmp_path),
                     journal_path=os.path.join(str(tmp_path), "journal"),
                     socket_path=os.path.join(str(tmp_path), "mr.sock"),
                     wait_sleep_s=0.02, **kw)


def test_resume_restores_completions(tmp_path):
    files = [f"f{i}" for i in range(4)]
    c1 = Coordinator(files, 5, _cfg(tmp_path))
    c1.map_complete({"TaskNumber": 1})
    c1.map_complete({"TaskNumber": 3})
    c1.map_complete({"TaskNumber": 3})  # duplicate: journaled once
    c1.close()

    c2 = Coordinator(files, 5, _cfg(tmp_path))
    assert c2.c_map == 2
    assert c2.map_log[1] == 2 and c2.map_log[3] == 2
    assert c2.map_log[0] == 0 and c2.map_log[2] == 0
    assert c2.c_reduce == 0
    c2.close()

    # the duplicate completion was journaled exactly once
    with open(os.path.join(str(tmp_path), "journal")) as f:
        lines = [l for l in f if '"map"' in l]
    assert len(lines) == 2


def test_resume_refuses_different_job(tmp_path):
    c1 = Coordinator(["a", "b"], 3, _cfg(tmp_path))
    c1.map_complete({"TaskNumber": 0})
    c1.close()
    with pytest.raises(SystemExit):
        Journal(os.path.join(str(tmp_path), "journal"),
                ["a", "DIFFERENT"], 3).replay()
    with pytest.raises(SystemExit):
        Journal(os.path.join(str(tmp_path), "journal"), ["a", "b"], 4).replay()


def test_torn_tail_line_ignored(tmp_path):
    c1 = Coordinator(["a", "b"], 3, _cfg(tmp_path))
    c1.map_complete({"TaskNumber": 0})
    c1.close()
    with open(os.path.join(str(tmp_path), "journal"), "a") as f:
        f.write('{"kind": "map", "ta')  # crash mid-write
    c2 = Coordinator(["a", "b"], 3, _cfg(tmp_path))
    assert c2.c_map == 1
    c2.close()


def test_torn_tail_truncated_before_append(tmp_path):
    """A record appended after a torn tail must not merge into it; the
    partial line is truncated away, so a THIRD incarnation still replays
    every completion written after the crash."""
    c1 = Coordinator(["a", "b", "c"], 3, _cfg(tmp_path))
    c1.map_complete({"TaskNumber": 0})
    c1.close()
    path = os.path.join(str(tmp_path), "journal")
    with open(path, "a") as f:
        f.write('{"kind": "map", "task":')  # crash mid-write
    c2 = Coordinator(["a", "b", "c"], 3, _cfg(tmp_path))
    assert c2.c_map == 1
    c2.map_complete({"TaskNumber": 2})
    c2.close()
    c3 = Coordinator(["a", "b", "c"], 3, _cfg(tmp_path))
    assert c3.c_map == 2 and c3.map_log[0] == 2 and c3.map_log[2] == 2
    c3.close()


def test_corrupt_midfile_record_truncated_for_future_appends(tmp_path):
    """A corrupt record in the MIDDLE of the journal (bit flip that still
    ends in newline) stops replay there — safe, re-execution is idempotent —
    and open() truncates at the corruption so completions appended by the
    resumed coordinator are replayable by a THIRD incarnation.  Without the
    truncation the journal is poisoned forever: everything after the bad
    record is invisible to every future resume."""
    files = ["a", "b", "c", "d"]
    c1 = Coordinator(files, 3, _cfg(tmp_path))
    c1.map_complete({"TaskNumber": 0})
    c1.map_complete({"TaskNumber": 1})
    c1.close()
    path = os.path.join(str(tmp_path), "journal")
    with open(path, "rb+") as f:
        data = f.read()
        # corrupt the SECOND map record (flip its task id), keeping
        # valid JSON + trailing newline — the record's rcrc no longer
        # matches its payload, so replay must treat it as corrupt
        bad = data.replace(b'"task":1}', b'"task":9}')
        assert bad != data
        f.seek(0)
        f.truncate()
        f.write(bad)

    c2 = Coordinator(files, 3, _cfg(tmp_path))
    assert c2.c_map == 1  # replay stopped at the corrupt record
    c2.map_complete({"TaskNumber": 3})
    c2.close()

    c3 = Coordinator(files, 3, _cfg(tmp_path))
    assert c3.c_map == 2  # task 0 (pre-corruption) + task 3 (post-repair)
    assert c3.map_log[0] == 2 and c3.map_log[3] == 2
    c3.close()


def test_empty_journal_file_gets_header(tmp_path):
    """Crash between file creation and header write must not brick resume."""
    path = os.path.join(str(tmp_path), "journal")
    open(path, "w").close()  # exists, zero bytes
    c1 = Coordinator(["a", "b"], 3, _cfg(tmp_path))
    c1.map_complete({"TaskNumber": 1})
    c1.close()
    c2 = Coordinator(["a", "b"], 3, _cfg(tmp_path))
    assert c2.c_map == 1
    c2.close()


@pytest.mark.slow
def test_coordinator_death_and_resume_full_job(tmp_path):
    """Kill the coordinator mid-job; a resumed one finishes with parity."""
    wd = str(tmp_path)
    files = ensure_corpus(os.path.join(wd, "inputs"), n_files=6,
                          file_size=50_000)
    want = oracle_output("wc", files, wd)
    mapf, reducef = load_plugin("wc")

    def run_workers(cfg, n=2):
        ws = [threading.Thread(target=worker_loop, args=(mapf, reducef, cfg),
                               daemon=True) for _ in range(n)]
        for w in ws:
            w.start()
        return ws

    cfg = _cfg(tmp_path)
    c1 = make_coordinator(files, 10, cfg)
    ws = run_workers(cfg)
    deadline = time.time() + 60
    while c1.c_map < 3:  # let part of the map phase commit
        assert time.time() < deadline
        time.sleep(0.01)
    c1.close()  # coordinator "dies"; workers exit on CoordinatorGone
    for w in ws:
        w.join(timeout=10)

    c2 = make_coordinator(files, 10, cfg)  # resume from the journal
    assert c2.c_map >= 3  # restored progress, no re-execution of those maps
    ws = run_workers(cfg)
    while not c2.done():
        assert time.time() < deadline, "resumed job hung"
        time.sleep(0.05)
    for w in ws:
        w.join(timeout=10)
    c2.close()

    assert merged_output(wd) == want
