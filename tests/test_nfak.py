"""NFA matrix-scan grep tier (ops/nfak.py): differential vs host re,
routing contract, multi-block correctness, and grammar fuzz."""

import os
import random
import re

import pytest

pytest.importorskip("jax")

from dsi_tpu.apps import grep, tpu_grep
from dsi_tpu.ops.nfak import nfagrep_host_result, parse_nfa_pattern

TEXT = (b"the quick brown fox\njumps over the lazy dogs\n"
        b"no match here\ncolour and color\nab ac abc abbbc\n"
        b"42 is the answer\n\nfox")


@pytest.fixture(autouse=True)
def _force_device_dispatch(monkeypatch):
    """These tests exercise the kernel itself; pin past the cost-model
    gate (which routes to host wherever the kernel measures slower —
    its own tests below override the pin)."""
    monkeypatch.setenv("DSI_NFA_DISPATCH", "device")


def oracle(data: bytes, pat: str):
    return [ln for ln in data.decode().split("\n") if re.search(pat, ln)]


@pytest.mark.parametrize("pat", [
    "ab*c", "colou?r", "[0-9]+", "a.*z",          # variable-length core
    "qu+ick", "o[ux]*r", "a?b?c", "x*y",          # modifier mix
    "^the", "dogs$", "^a.*c$", "f.x$", "x+$",     # anchors
    "ab*c|fox", "z*fox|dogs?$", "^x*y|[0-9]+",    # alternation
    "fox", "the",                                 # plain (tier overlap)
    r"\d+ is", r"\w+ \w+", r"[a-z]+\s[a-z]+",     # escape classes
])
def test_matches_re_oracle(pat):
    got = nfagrep_host_result(TEXT, pat)
    assert got is not None, f"{pat!r} unexpectedly routed to host"
    assert got == oracle(TEXT, pat), pat


@pytest.mark.parametrize("pat", [
    "ab{2}c", "b{2,}", "a{1,3}b", "[0-9]{2}",   # bounded reps (expanded)
    "ab*?c", "qu+?ick", "colou??r", "b{1,3}?",  # non-greedy == greedy
    "a{2", "x}y", "e}?",                        # literal braces, re-style
    "x{,2}s", "o{,1}x",                         # {,n} == {0,n} (re>=3.11)
])
def test_bounded_reps_and_nongreedy_match_oracle(pat):
    got = nfagrep_host_result(TEXT, pat)
    assert got is not None, f"{pat!r} unexpectedly routed to host"
    assert got == oracle(TEXT, pat), pat


@pytest.mark.parametrize("pat", [
    "a*",          # nullable: matches every line incl. empty — host
    "x*y*",        # nullable via both atoms
    "a{0,3}",      # nullable via bounded rep
    "^$",          # empty anchored
    "(ab)*",       # group
    "a{3,2}",      # inverted bounds: re errors
    "{2}",         # bare quantifier: re 'nothing to repeat'
    "a{2}{3}",     # multiple repeat: re errors
    "a**",         # stacked modifiers
    "a|",          # empty branch
    r"\bword",     # word boundary
    "h\xe9llo",    # non-ASCII
    "a" * 60,      # wider than the largest state bucket
    "a{1,60}",     # expansion exceeds the state bucket
])
def test_ineligible_routes_to_host(pat):
    assert nfagrep_host_result(TEXT, pat) is None


def test_nul_data_routes_to_host():
    assert nfagrep_host_result(b"a\x00b\nfox\n", "fox+") is None


def test_stray_modifier_routes_to_host():
    # re rejects '*a' as an error; the tier must not silently treat the
    # modifier as a literal.
    assert nfagrep_host_result(TEXT, "*a") is None
    assert nfagrep_host_result(TEXT, "a|+b") is None


def test_cold_compile_gate(monkeypatch):
    """On an accelerator platform the tier only serves patterns whose
    program is already persisted (or when the warm script says cold
    compiles are its job); CPU platforms are always ready."""
    import dsi_tpu.ops.nfak as nfak

    assert nfak._device_ready(1024, 16, 256, 128)  # CPU: always

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(nfak.jax, "devices", lambda: [_FakeDev()])
    monkeypatch.setattr(
        "dsi_tpu.backends.aotcache.is_persisted",
        lambda *a, **k: False)
    assert not nfak._device_ready(1024, 16, 256, 128)
    monkeypatch.setenv("DSI_NFA_COLD_OK", "1")
    assert nfak._device_ready(1024, 16, 256, 128)
    monkeypatch.delenv("DSI_NFA_COLD_OK")
    monkeypatch.setattr(
        "dsi_tpu.backends.aotcache.is_persisted",
        lambda *a, **k: True)
    assert nfak._device_ready(1024, 16, 256, 128)


def test_overflow_rung_gated(monkeypatch):
    """ADVICE r4 (medium): the l_cap retry schedule escalates to the n+1
    rung on line-count overflow, a separately compiled shape.  On an
    accelerator with only the FIRST rung persisted, the tier must fall
    back to host rather than cold-compile the escalation rung in-task."""
    import numpy as np

    import dsi_tpu.ops.nfak as nfak
    from dsi_tpu.ops.grepk import line_cap_rungs

    compiled_caps = []

    def fake_ready(n, s, b, l_cap):
        return l_cap == line_cap_rungs(n)[0]  # only rung 1 persisted

    def fake_compiled(n, s, b, l_cap):
        compiled_caps.append(l_cap)

        def run(chunk, table, v0):
            # Overflowing result: forces escalation to the next rung.
            return (np.zeros(l_cap, np.int32), np.int32(l_cap + 5),
                    np.bool_(True))

        return run

    monkeypatch.setattr(nfak, "_device_ready", fake_ready)
    monkeypatch.setattr(nfak, "_nfa_compiled", fake_compiled)
    data = b"ab\n" * 64  # average line 3 B < 8 B: rung 1 overflows
    assert nfak.nfagrep_host_result(data, "ab+") is None
    n = len(nfak._pad_pow2(data))
    assert compiled_caps == [line_cap_rungs(n)[0]], \
        "escalation rung must never be compiled when not persisted"


def test_multi_block_spanning():
    """Data far larger than one 256-byte scan block, with matches that
    sit inside, start, and end at block boundaries."""
    rng = random.Random(5)
    lines = []
    for i in range(200):
        pad = "".join(rng.choices("qwert yuiop", k=rng.randint(0, 40)))
        lines.append(pad + ("abbbc" if i % 7 == 0 else "")
                     + ("xyz" if i % 11 == 0 else ""))
    data = "\n".join(lines).encode()
    for pat in ["ab+c", "xy?z$", "^q.*c"]:
        assert nfagrep_host_result(data, pat) == oracle(data, pat), pat


def test_empty_lines_and_no_trailing_newline():
    data = b"\n\nab\n\nabb\n"
    assert nfagrep_host_result(data, "ab+") == oracle(data, "ab+")
    data2 = b"ab\n\nabb"  # final line without newline
    assert nfagrep_host_result(data2, "ab+$") == oracle(data2, "ab+$")


def test_line_overflow_retry():
    data = b"\n" * 3000 + b"needle\n" + b"\n" * 3000 + b"needles\n"
    assert nfagrep_host_result(data, "needles?$") == ["needle", "needles"]


def test_tpu_map_dispatches_tier4():
    os.environ["DSI_GREP_PATTERN"] = "qu+ick|dogs$"
    try:
        kva = tpu_grep.tpu_map("f", TEXT)
    finally:
        del os.environ["DSI_GREP_PATTERN"]
    assert kva is not None
    assert [kv.key for kv in kva] == oracle(TEXT, "qu+ick|dogs$")


def test_pattern_independent_program():
    """The compiled program is shared across patterns (table ships as an
    argument): two different patterns at one chunk shape must not
    trigger a second compile."""
    from dsi_tpu.backends import aotcache

    data = b"alpha beta\ngamma delta\n" * 8
    nfagrep_host_result(data, "al.*a")
    before = aotcache.stats["compiles"]
    nfagrep_host_result(data, "de[kl]ta+")
    assert aotcache.stats["compiles"] == before


def test_fuzz_generated_patterns_vs_oracle():
    """Patterns built from the supported grammar with random modifiers
    and alternation; every accepted pattern must agree with the re
    oracle, and None routes are only allowed for nullable collapses."""
    rng = random.Random(37)
    alphabet = "abcxyzAB01 .,;"

    def gen_atom():
        r = rng.random()
        if r < 0.45:
            return rng.choice("abcxyzAB")
        if r < 0.6:
            return "."
        if r < 0.72:
            return rng.choice([r"\d", r"\w", r"\s"])
        neg = "^" if rng.random() < 0.25 else ""
        items = "".join(rng.sample("abcxyz019", rng.randint(1, 3)))
        return f"[{neg}{items}]"

    def gen_branch():
        atoms = []
        for _ in range(rng.randint(1, 5)):
            a = gen_atom()
            r = rng.random()
            if r < 0.3:
                a += rng.choice("*+?")
                if rng.random() < 0.25:
                    a += "?"  # non-greedy
            elif r < 0.45:
                lo = rng.randint(0, 2)
                hi = rng.choice(["", lo + rng.randint(0, 2)])
                a += ("{%d}" % lo if hi == lo and rng.random() < 0.5
                      else "{%d,%s}" % (lo, hi))
            atoms.append(a)
        b = "".join(atoms)
        if rng.random() < 0.15:
            b = "^" + b
        if rng.random() < 0.15:
            b = b + "$"
        return b

    accepted = 0
    for trial in range(60):
        pattern = "|".join(gen_branch()
                           for _ in range(rng.randint(1, 3)))
        lines = ["".join(rng.choices(alphabet, k=rng.randint(0, 30)))
                 for _ in range(rng.randint(1, 40))]
        data = "\n".join(lines).encode()
        got = nfagrep_host_result(data, pattern)
        if got is None:
            # Only legitimate host routes: a nullable pattern.
            assert parse_nfa_pattern(pattern) is None, (trial, pattern)
            continue
        accepted += 1
        assert got == oracle(data, pattern), (trial, pattern, lines)
    assert accepted >= 30, "fuzz generated too few device-eligible patterns"


# ── tier-4 dispatch cost model (round 5) ───────────────────────────────


def test_cost_model_pins(monkeypatch):
    import dsi_tpu.ops.nfak as nfak

    monkeypatch.setenv("DSI_NFA_DISPATCH", "host")
    assert nfak.tier4_preferred(16) is False
    assert nfak.nfagrep_host_result(TEXT, "qu+ick") is None  # host serves
    monkeypatch.setenv("DSI_NFA_DISPATCH", "device")
    assert nfak.tier4_preferred(16) is True


def test_cost_model_routes_to_winner(monkeypatch):
    import dsi_tpu.ops.nfak as nfak

    monkeypatch.delenv("DSI_NFA_DISPATCH", raising=False)
    key = nfak._cost_key(16)
    monkeypatch.setitem(nfak._cost_cache, key,
                        {"host_mbps": 20.0, "kernel_mbps": 2.0})
    nfak._cost_loaded = True
    assert nfak.tier4_preferred(16) is False
    assert nfak.nfagrep_host_result(TEXT, "qu+ick") is None
    monkeypatch.setitem(nfak._cost_cache, key,
                        {"host_mbps": 2.0, "kernel_mbps": 20.0})
    assert nfak.tier4_preferred(16) is True
    got = nfak.nfagrep_host_result(TEXT, "qu+ick")
    assert got == oracle(TEXT, "qu+ick")


def test_cost_model_cpu_calibrates_and_persists(monkeypatch, tmp_path):
    import dsi_tpu.ops.nfak as nfak

    monkeypatch.delenv("DSI_NFA_DISPATCH", raising=False)
    monkeypatch.setenv("DSI_AOT_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(nfak, "_cost_cache", {})
    monkeypatch.setattr(nfak, "_cost_loaded", False)
    pref = nfak.tier4_preferred(16)
    assert pref in (True, False)  # measured, not None
    entry = nfak._load_costs()[nfak._cost_key(16)]
    assert entry["host_mbps"] > 0 and entry["kernel_mbps"] > 0
    import json

    on_disk = json.load(open(tmp_path / "nfa_cost.json"))
    assert on_disk[nfak._cost_key(16)] == entry
