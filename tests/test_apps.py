"""App semantics: wc tokenization vs the Go spec, grep, indexer."""

import os

from dsi_tpu.apps import grep, indexer, wc
from dsi_tpu.mr.types import KeyValue


def test_wc_splits_on_non_letters():
    # Go splits on ANY non-letter rune, including digits and underscore
    # (mrapps/wc.go:23: !unicode.IsLetter).
    kva = wc.Map("f", "one two2three_four\nfive,six")
    assert [kv.key for kv in kva] == ["one", "two", "three", "four", "five", "six"]
    assert all(kv.value == "1" for kv in kva)


def test_wc_reduce_counts():
    assert wc.Reduce("word", ["1", "1", "1"]) == "3"
    assert wc.Reduce("word", []) == "0"


def test_wc_empty_and_punct_only():
    assert wc.Map("f", "") == []
    assert wc.Map("f", "123 ... __ \n") == []


def test_wc_tokenizer_go_isletter_unicode_parity():
    # Go's unicode.IsLetter is category L ONLY: Ⅳ (Nl, Roman numeral) and
    # ² (No) are separators, while ª (Lo) and µ (Ll) are letters.  A \w-based
    # regex gets these wrong (VERDICT r1 weakness #3: 'bⅣcªd' must be two
    # words, not one).
    assert [kv.key for kv in wc.Map("f", "bⅣcªd")] == ["b", "cªd"]
    assert [kv.key for kv in wc.Map("f", "x²y µz 漢字")] == \
        ["x", "y", "µz", "漢字"]
    # Combining marks (Mn) split words under Go semantics: e + U+0301 is
    # two runs "e", nothing — the mark itself is not a letter.
    assert [kv.key for kv in wc.Map("f", "cafe\u0301s")] == ["cafe", "s"]
    assert [kv.key for kv in wc.Map("f", "caf\u00e9s")] == ["caf\u00e9s"]


def test_grep_matches_lines(monkeypatch):
    monkeypatch.setenv("DSI_GREP_PATTERN", r"wh(ale|ite)")
    kva = grep.Map("f", "the white whale\nno match here\nwhale ho\n")
    assert [kv.key for kv in kva] == ["the white whale", "whale ho"]
    assert grep.Reduce("the white whale", ["", ""]) == "2"


def test_grep_default_matches_nothing(monkeypatch):
    monkeypatch.delenv("DSI_GREP_PATTERN", raising=False)
    assert grep.Map("f", "anything\nat all") == []


def test_indexer_dedups_within_doc_and_sorts():
    kva = indexer.Map("doc1", "apple banana apple")
    assert kva == [KeyValue("apple", "doc1"), KeyValue("banana", "doc1")]
    assert indexer.Reduce("apple", ["doc2", "doc1", "doc2"]) == "2 doc1,doc2"
