"""Known-bad: engine stat keys drifting out of the registry schema."""

from dsi_tpu.obs import metrics_scope, span as _span


def engine_run():
    stats = metrics_scope("stream")
    stats["steps"] = 0                    # clean: schema key
    stats["step_throughputz"] = 1.0       # EXPECT: metric-schema
    stats.setdefault("batch_s", 0.0)      # clean: legacy alias
    stats.setdefault("warmup_fraction", 0)  # EXPECT: metric-schema
    with _span("kernel", stats=stats, key="kernal_s"):  # EXPECT: metric-schema
        pass
    return stats


def plan_run():
    # The ISSUE-14 plan-scope keys are IN the schema: none of these may
    # fire (the not-overfire half of the gate).
    sc = metrics_scope("plan")
    sc["plan_stages"] = 2                  # clean: schema key
    sc.setdefault("plan_intermediate_bytes", 0)  # clean: schema key
    sc.update({"plan_handoff": "device"})  # clean: schema key
    with _span("plan", stats=sc, key="plan_s"):
        pass
    sc["plan_commit_bytez"] = 1            # EXPECT: metric-schema
    return sc


def serve_metrics():
    # ISSUE-19 half of the rule: dsi_serve_* literals are the daemon's
    # /metrics surface and must come from registry.SERVE_SERIES.
    L = ["dsi_serve_jobs_total 3"]          # clean: registered series
    L.append("dsi_serve_junk_total 1")      # EXPECT: metric-schema
    lab = 'tenant="a"'
    L.append(f"dsi_serve_tenant_steps{{{lab}}} 2")  # clean: registered
    L.append(f"dsi_serve_bogus_{lab} 1")    # EXPECT: metric-schema
    return L
