"""Known-bad: engine stat keys drifting out of the registry schema."""

from dsi_tpu.obs import metrics_scope, span as _span


def engine_run():
    stats = metrics_scope("stream")
    stats["steps"] = 0                    # clean: schema key
    stats["step_throughputz"] = 1.0       # EXPECT: metric-schema
    stats.setdefault("batch_s", 0.0)      # clean: legacy alias
    stats.setdefault("warmup_fraction", 0)  # EXPECT: metric-schema
    with _span("kernel", stats=stats, key="kernal_s"):  # EXPECT: metric-schema
        pass
    return stats
