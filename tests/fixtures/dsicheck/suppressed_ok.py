"""Fixture proving the ``# dsicheck: allow[...]`` escape hatch: every
violation here is annotated, so the engine reports them only as
suppressed."""


def annotated_same_line(path, payload):
    with open(path, "wb") as f:  # dsicheck: allow[raw-write] fixture
        f.write(payload)


def annotated_block_above(path, payload):
    # dsicheck: allow[raw-write] multi-line reason comments anchor to
    # the next code line, so the reason can actually explain itself
    with open(path, "wb") as f:
        f.write(payload)


def annotated_wildcard(path):
    f = open(path, "a")  # dsicheck: allow[all] wildcard escape
    return f
