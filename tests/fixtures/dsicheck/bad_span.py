"""Known-bad: span-discipline violations."""

from dsi_tpu.obs import span as _span


def leaked_span(stats):
    sp = _span("upload", stats=stats, key="upload_s")  # EXPECT: span-discipline
    sp.__enter__()
    return sp


def off_schema_name(stats):
    with _span("uplaod", stats=stats, key="upload_s"):  # EXPECT: span-discipline
        pass


def off_taxonomy_lane():
    with _span("fold", lane="device-stuff"):  # EXPECT: span-discipline
        pass


def clean(stats):
    with _span("kernel", stats=stats, key="kernel_s"):
        pass


def clean_plan(stats):
    # The ISSUE-14 plan lane/names are pinned in SPAN_NAMES/LANES: a
    # plan-layer span must NOT fire the rule...
    with _span("plan", stats=stats, key="plan_s", stage="grep"):
        pass
    with _span("stage_commit", lane="plan", stats=stats,
               key="stage_commit_s"):
        pass


def off_plan_name():
    with _span("stage_comit", lane="plan"):  # EXPECT: span-discipline
        pass
