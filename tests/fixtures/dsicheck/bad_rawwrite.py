"""Known-bad: writes bypassing the atomicio durable path."""

import io

import numpy as np


def torn_manifest(path, payload):
    with open(path, "wb") as f:  # EXPECT: raw-write
        f.write(payload)


def appender(path):
    f = open(path, mode="a")  # EXPECT: raw-write
    f.write("x\n")


def direct_savez(path, arrays):
    np.savez(path, **arrays)  # EXPECT: raw-write


def buffered_savez_is_clean(arrays):
    buf = io.BytesIO()
    np.savez(buf, **arrays)  # clean: serialize-to-buffer idiom
    return buf.getvalue()


def reading_is_clean(path):
    with open(path) as f:  # clean: default mode 'r'
        return f.read()
