"""Known-bad: writes bypassing the atomicio durable path."""

import io

import numpy as np


def torn_manifest(path, payload):
    with open(path, "wb") as f:  # EXPECT: raw-write
        f.write(payload)


def appender(path):
    f = open(path, mode="a")  # EXPECT: raw-write
    f.write("x\n")


def direct_savez(path, arrays):
    np.savez(path, **arrays)  # EXPECT: raw-write


def direct_savez_compressed(path, arrays):
    np.savez_compressed(path, **arrays)  # EXPECT: raw-write


def buffered_savez_is_clean(arrays):
    buf = io.BytesIO()
    np.savez(buf, **arrays)  # clean: serialize-to-buffer idiom
    return buf.getvalue()


def buffered_savez_compressed_is_clean(arrays):
    # The compressed-delta store path (ISSUE 13): same idiom, zlib'd.
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)  # clean: serialize-to-buffer
    return buf.getvalue()


def annotated_buffer_is_clean(arrays):
    buf: io.BytesIO = io.BytesIO()
    np.savez_compressed(buf, **arrays)  # clean: annotated assignment
    return buf.getvalue()


def inline_buffer_is_clean(arrays):
    np.savez_compressed(io.BytesIO(), **arrays)  # clean: inline buffer


def reading_is_clean(path):
    with open(path) as f:  # clean: default mode 'r'
        return f.read()
