"""Known-bad: guarded state mutated outside its owning lock."""

import heapq
import threading

_lock = threading.Lock()
_active = None


def set_active(v):
    global _active
    with _lock:
        _active = v


def clear_active():
    global _active
    _active = None  # EXPECT: lock-guard (module global)


class Scheduler:
    def __init__(self):
        self.mu = threading.Lock()
        self._wake = threading.Condition(self.mu)
        self._queue = []
        self._jobs = {}
        self._seq = 0
        self._boot()  # construction-time helper: exempt

    def _boot(self):
        self._jobs["seed"] = 1  # clean: reachable only from __init__

    def submit(self, job):
        with self._wake:  # Condition aliases mu: counts as holding it
            self._seq += 1
            self._jobs[job] = self._seq
            heapq.heappush(self._queue, job)

    def _admit(self):
        # every call site holds the lock -> analyzed as lock-held
        self._jobs.pop("seed", None)

    def scheduler_loop(self):
        with self.mu:
            self._admit()
        self._seq += 1  # EXPECT: lock-guard (unlocked counter bump)
        self._queue.append("x")  # EXPECT: lock-guard (unlocked mutator)

    def racy_drain(self):
        heapq.heappop(self._queue)  # EXPECT: lock-guard (heapq escape)
