"""Known-bad: nondeterminism inside jit-compiled bodies."""

import os
import random
import time

import jax
import numpy as np

from dsi_tpu.backends.aotcache import cached_compile


@jax.jit
def decorated_impure(x):
    t = time.perf_counter()  # EXPECT: jit-purity
    return x + t


def _step_impl(x):
    if os.environ.get("DSI_FAST"):  # EXPECT: jit-purity
        return x
    return x + random.random()  # EXPECT: jit-purity


def build(example):
    return cached_compile("step", _step_impl, (example,))


def _noise_impl(x):
    return x + np.random.rand()  # EXPECT: jit-purity


_noise = jax.jit(_noise_impl)


def host_side_is_clean():
    return time.perf_counter()  # clean: not a jit target
