"""Known-bad: donated buffers read after the donating call."""

from dsi_tpu.backends.aotcache import cached_compile

_TABLE_DONATE = (0, 1)


def local_factory_read_after_donate(chunk, table, impl):
    fold = cached_compile("fold", impl, (table, chunk),
                          donate_argnums=(0,))
    out = fold(table, chunk)
    return table.sum(), out  # EXPECT: donation-after-use


def module_constant_positions(rows, nus, impl):
    fold = cached_compile("fold2", impl, (rows, nus),
                          donate_argnums=_TABLE_DONATE)
    fold(rows, nus)
    nus = 0                # re-bound from scratch: clean
    return rows[0], nus    # EXPECT: donation-after-use


def rebinding_is_clean(table, chunk, impl):
    fold = cached_compile("fold3", impl, (table, chunk),
                          donate_argnums=(0,))
    table = fold(table, chunk)   # the idiomatic kill
    return table.sum()           # clean: re-bound name


class AttrDonor:
    def __init__(self, impl, rows):
        self._fold = cached_compile("fold4", impl, (rows,),
                                    donate_argnums=(0,))
        self.rows = rows

    def step(self):
        out = self._fold(self.rows)
        return self.rows.sum(), out  # EXPECT: donation-after-use
