"""Property test for the framed journal (ISSUE 20 satellite): truncate
the file at EVERY byte offset and corrupt every byte — replay must
always yield a clean line-aligned prefix of the original record stream
(or refuse loudly), never an invented or reordered task table.

Exhaustive rather than sampled: the journal under test is a few hundred
bytes, so the full offset sweep is cheap AND deterministic — strictly
stronger than a property-test framework's random draw (``hypothesis``
is not in the image; the sweep makes it unnecessary).
"""

from __future__ import annotations

import pytest

from dsi_tpu.mr.journal import Journal

FILES = ["a.txt", "b.txt"]
N_REDUCE = 3
N_SHARDS = 4


def _build(path: str) -> bytes:
    """A journal exercising every record kind the framing covers."""
    j = Journal(path, FILES, N_REDUCE, N_SHARDS)
    j.replay()
    j.open()
    j.record("map", 0, {"addr": "127.0.0.1:9001", "sizes": [3, 5, 7]})
    j.record("map", 1)
    j.record("reduce", 2, {"addr": "127.0.0.1:9001",
                           "name": "mr-out-2", "crc": 77})
    j.record_shard(1, 0, 12345)
    j.record_resplit(2, [(0, 10), (10, 20)])
    j.record_subshard(2, 0, 1, 999)
    j.record("reduce", 0)
    j.close()
    with open(path, "rb") as f:
        return f.read()


def _state(path: str):
    """Everything replay() reconstructs, as one comparable value."""
    j = Journal(path, FILES, N_REDUCE, N_SHARDS)
    maps, reduces = j.replay()
    return (sorted(maps), sorted(reduces), dict(j.shard_commits),
            dict(j.resplits), dict(j.subshard_commits),
            dict(j.map_locations), dict(j.map_sizes),
            dict(j.out_locations))


def _line_starts(data: bytes):
    """Byte offset of every line start, plus the end-of-file offset
    (the journal always ends with a newline)."""
    starts = [0]
    for i, b in enumerate(data):
        if b == 0x0A:
            starts.append(i + 1)
    return starts


def _boundary_states(data: bytes, probe: str):
    states = {}
    for b in _line_starts(data):
        with open(probe, "wb") as f:
            f.write(data[:b])
        states[b] = _state(probe)
    return states


def test_truncate_every_offset_replays_clean_prefix(tmp_path):
    full = str(tmp_path / "full.journal")
    data = _build(full)
    assert len(data) > 100
    probe = str(tmp_path / "probe.journal")
    boundary = _boundary_states(data, probe)
    starts = _line_starts(data)
    for t in range(len(data) + 1):
        with open(probe, "wb") as f:
            f.write(data[:t])
        floor = max(b for b in starts if b <= t)
        # Truncation can never manufacture a parseable-but-different
        # header, so replay must not refuse — it must degrade to the
        # longest clean line-aligned prefix, exactly.
        assert _state(probe) == boundary[floor], \
            f"truncation at byte {t} did not replay the clean prefix"


def test_truncate_then_repair_then_append_replays(tmp_path):
    """open() after a torn replay truncates the wreckage so appends
    land in replayable territory — at every cut point."""
    full = str(tmp_path / "full.journal")
    data = _build(full)
    probe = str(tmp_path / "probe.journal")
    for t in range(len(data) + 1):
        with open(probe, "wb") as f:
            f.write(data[:t])
        j = Journal(probe, FILES, N_REDUCE, N_SHARDS)
        maps_before, _ = j.replay()
        j.open()
        j.record("map", 1)  # idempotent completion re-record
        j.close()
        maps_after = _state(probe)[0]
        assert 1 in maps_after, \
            f"append after repair at cut {t} did not replay"
        # Nothing that replayed before the repair may vanish after it.
        assert set(maps_before) <= set(maps_after)


def test_flip_every_byte_never_invents_state(tmp_path):
    """Single-byte corruption anywhere: replay lands on SOME clean
    line-aligned prefix (usually cut at the corrupted line — the record
    CRC or the JSON layer stops it) or refuses loudly at the header.
    A flip that only grazes the ``rcrc`` framing key demotes the record
    to a legacy unframed one with identical semantics, which replays to
    the full (correct) state — also a clean prefix.  What must NEVER
    happen is a state outside that prefix chain: a silently different
    task table."""
    full = str(tmp_path / "full.journal")
    data = _build(full)
    probe = str(tmp_path / "probe.journal")
    boundary = _boundary_states(data, probe)
    acceptable = {repr(s) for s in boundary.values()}
    header_end = _line_starts(data)[1]
    for p in range(len(data)):
        mutated = bytearray(data)
        mutated[p] ^= 0x01
        with open(probe, "wb") as f:
            f.write(bytes(mutated))
        try:
            got = _state(probe)
        except SystemExit:
            # A corrupted header that still frames as valid JSON reads
            # as "a different job" — refusing is the correct loud path.
            assert p < header_end, \
                f"non-header corruption at byte {p} raised SystemExit"
            continue
        assert repr(got) in acceptable, \
            f"corruption at byte {p} invented state {got!r}"


def test_header_mismatch_refuses_loudly(tmp_path):
    full = str(tmp_path / "full.journal")
    _build(full)
    j = Journal(full, FILES + ["c.txt"], N_REDUCE, N_SHARDS)
    with pytest.raises(SystemExit):
        j.replay()
