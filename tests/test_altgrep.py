"""Alternation grep tier (ops/altk.py): differential vs host re, split
semantics, and fallback routing."""

import os

import pytest

pytest.importorskip("jax")

from dsi_tpu.apps import grep, tpu_grep
from dsi_tpu.ops.altk import altgrep_host_result, split_alternation

TEXT = (b"the quick brown fox\njumps over The lazy dog\n"
        b"no match here\nCats and dogs\n42 is the answer\n\nfox")


def host_lines(data: bytes, pattern: str):
    os.environ["DSI_GREP_PATTERN"] = pattern
    try:
        return [kv.key for kv in grep.Map("f", data.decode())]
    finally:
        del os.environ["DSI_GREP_PATTERN"]


def test_split_alternation():
    assert split_alternation("the|and") == ["the", "and"]
    assert split_alternation("a|b|c") == ["a", "b", "c"]
    assert split_alternation("a|a|b") == ["a", "b"]  # dedup, order kept
    assert split_alternation("a|a") is None          # collapses to 1 branch
    assert split_alternation(r"a\|b") is None        # escaped: literal |
    assert split_alternation(r"a\||b") == [r"a\|", "b"]
    assert split_alternation("[a|b]x") is None       # | inside a class
    assert split_alternation("[Tt]he|[Aa]nd") == ["[Tt]he", "[Aa]nd"]
    assert split_alternation("a|") is None           # empty branch
    assert split_alternation("|a") is None
    assert split_alternation("plain") is None        # no alternation
    assert split_alternation("[ab|cd") is None       # unterminated class


@pytest.mark.parametrize("pat", [
    "the|and",                # literal | literal
    "fox|dog|Cats",           # three branches
    "[Tt]he|[Cc]ats",         # class | class
    "fox|[Dd]og",             # mixed tiers
    "^the|dog$",              # per-branch anchors, re binding
    r"\d\d|lazy",             # escape-class branch
    "zzz|qqq",                # no matches
    "e| ",                    # high-frequency single bytes
])
def test_alternation_matches_host_regex(pat):
    got = altgrep_host_result(TEXT, pat)
    assert got is not None, f"{pat!r} unexpectedly routed to host"
    assert got == host_lines(TEXT, pat)


@pytest.mark.parametrize("pat", [
    "a|b*",        # variable-length branch
    "(a|b)",       # group
    "a|",          # empty branch
    "plain",       # not an alternation (tier 1/2 territory)
    "a|h\xe9llo",  # non-ASCII branch
])
def test_ineligible_patterns_route_to_host(pat):
    assert altgrep_host_result(TEXT, pat) is None


def test_nul_data_with_class_branch_routes_to_host():
    assert altgrep_host_result(b"a\x00b\nthe\n", "[Tt]he|and") is None
    # ...but all-literal branches tolerate NUL (padding can't match them)
    assert altgrep_host_result(b"a\x00b\nthe\n", "the|and") == ["the"]


def test_branch_longer_than_data():
    assert altgrep_host_result(b"tiny\nthe\n", "the|" + "a" * 300) == ["the"]


def test_tpu_map_dispatches_alternation():
    os.environ["DSI_GREP_PATTERN"] = "fox|[Dd]og"
    try:
        kva = tpu_grep.tpu_map("f", TEXT)
    finally:
        del os.environ["DSI_GREP_PATTERN"]
    assert kva is not None
    assert [kv.key for kv in kva] == host_lines(TEXT, "fox|[Dd]og")


def test_line_overflow_retry_with_alternation():
    data = b"\n" * 3000 + b"needle\n" + b"\n" * 3000 + b"pin\n"
    assert altgrep_host_result(data, "needle|pin") == ["needle", "pin"]


def test_fuzz_generated_alternations_vs_oracle():
    """Alternations of branches drawn from the class-pattern grammar and
    plain literals: every generated pattern must be accepted and agree
    with the per-line re.search oracle (the same discipline as
    tests/test_ops_regexk.py's grammar fuzz)."""
    import random
    import re

    rng = random.Random(31)
    alphabet = "abcxyzAB01 .,;"

    def gen_branch():
        if rng.random() < 0.4:  # literal branch
            return "".join(rng.choices("abcxyzAB01", k=rng.randint(1, 4)))
        atoms = []
        for _ in range(rng.randint(1, 4)):
            r = rng.random()
            if r < 0.4:
                atoms.append(rng.choice("abcxyzAB"))
            elif r < 0.55:
                atoms.append(".")
            elif r < 0.7:
                atoms.append(rng.choice([r"\d", r"\w", r"\s"]))
            else:
                neg = "^" if rng.random() < 0.3 else ""
                items = "".join(rng.sample("abcxyz019", rng.randint(1, 3)))
                atoms.append(f"[{neg}{items}]")
        b = "".join(atoms)
        if rng.random() < 0.2:
            b = "^" + b
        if rng.random() < 0.2:
            b = b + "$"
        return b

    for trial in range(40):
        pattern = "|".join(gen_branch()
                           for _ in range(rng.randint(2, 4)))
        if split_alternation(pattern) is None:
            continue  # duplicate-free split may collapse below 2 branches
        lines = ["".join(rng.choices(alphabet, k=rng.randint(0, 24)))
                 for _ in range(rng.randint(1, 30))]
        data = "\n".join(lines).encode()
        got = altgrep_host_result(data, pattern)
        assert got is not None, (trial, pattern)
        want = [ln for ln in data.decode().split("\n")
                if re.search(pattern, ln)]
        assert got == want, (trial, pattern, lines)
