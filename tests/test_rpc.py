"""RPC layer: framed-JSON over Unix socket, dial-per-call semantics."""

import os

import pytest

from dsi_tpu.mr import rpc


def test_roundtrip(tmp_path):
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Echo": lambda a: {"got": a}})
    srv.start()
    try:
        ok, reply = rpc.call(sock, "Echo", {"x": 1})
        assert ok and reply == {"got": {"x": 1}}
    finally:
        srv.close()


def test_unknown_method_returns_not_ok(tmp_path):
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {})
    srv.start()
    try:
        ok, reply = rpc.call(sock, "Nope", {})
        assert not ok and reply is None
    finally:
        srv.close()


def test_dial_failure_raises_coordinator_gone(tmp_path):
    # Reference worker log.Fatals when the coordinator socket is gone
    # (mr/worker.go:176-178); we surface it as an exception the loop
    # treats as job-over.
    with pytest.raises(rpc.CoordinatorGone):
        rpc.call(str(tmp_path / "missing"), "X", {})


def test_stale_socket_file_is_replaced(tmp_path):
    sock = str(tmp_path / "s")
    open(sock, "w").close()  # stale file; server must os.remove it first
    srv = rpc.RpcServer(sock, {"M": lambda a: {}})
    srv.start()
    try:
        ok, _ = rpc.call(sock, "M", {})
        assert ok
    finally:
        srv.close()


def test_concurrent_calls(tmp_path):
    import threading
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Inc": lambda a: {"v": a["v"] + 1}})
    srv.start()
    errs = []

    def hit(i):
        ok, r = rpc.call(sock, "Inc", {"v": i})
        if not ok or r["v"] != i + 1:
            errs.append(i)

    try:
        ts = [threading.Thread(target=hit, args=(i,)) for i in range(32)]
        for t in ts: t.start()
        for t in ts: t.join()
        assert not errs
    finally:
        srv.close()


def test_tcp_roundtrip():
    """TCP transport: the reference's commented-out multi-host variant
    (mr/coordinator.go:124, mr/worker.go:173) as a first-class address."""
    calls = []
    srv = rpc.RpcServer("tcp:127.0.0.1:0",
                        {"Echo": lambda a: (calls.append(a) or a)})
    srv.start()
    try:
        addr = srv.address
        assert addr.startswith("tcp:127.0.0.1:")
        ok, reply = rpc.call(addr, "Echo", {"x": 42})
        assert ok and reply == {"x": 42} and calls == [{"x": 42}]
        ok, reply = rpc.call(addr, "NoSuch", {})
        assert not ok
    finally:
        srv.close()


def test_tcp_dead_port_raises_coordinator_gone():
    import pytest as _pytest

    with _pytest.raises(rpc.CoordinatorGone):
        rpc.call("tcp:127.0.0.1:1", "Echo", {})


def test_tcp_end_to_end_job(tmp_path):
    """Full distributed job with the control plane on TCP."""
    import os as _os

    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import make_coordinator
    from dsi_tpu.mr.plugin import load_plugin
    from dsi_tpu.mr.worker import worker_loop
    from dsi_tpu.utils.corpus import ensure_corpus
    from tests.harness import merged_output, oracle_output
    import threading
    import time as _time

    wd = str(tmp_path)
    files = ensure_corpus(_os.path.join(wd, "inputs"), n_files=3,
                          file_size=40_000)
    want = oracle_output("wc", files, wd)
    cfg = JobConfig(n_reduce=5, workdir=wd, socket_path="tcp:127.0.0.1:0",
                    wait_sleep_s=0.02)
    c = make_coordinator(files, 5, cfg)
    worker_cfg = JobConfig(n_reduce=5, workdir=wd,
                           socket_path=c.address(), wait_sleep_s=0.02)
    mapf, reducef = load_plugin("wc")
    try:
        ws = [threading.Thread(target=worker_loop,
                               args=(mapf, reducef, worker_cfg), daemon=True)
              for _ in range(2)]
        for w in ws:
            w.start()
        deadline = _time.time() + 60
        while not c.done():
            assert _time.time() < deadline
            _time.sleep(0.05)
        for w in ws:
            w.join(timeout=10)
    finally:
        c.close()
    assert merged_output(wd) == want


def test_malformed_tcp_address_is_coordinator_gone():
    import pytest as _pytest

    with _pytest.raises(rpc.CoordinatorGone):
        rpc.call("tcp:myhost", "Echo", {})  # operator typo: no port
    with _pytest.raises(ValueError):
        rpc.parse_address("tcp:")


def test_wildcard_bind_advertises_reachable_host():
    srv = rpc.RpcServer("tcp:0.0.0.0:0", {"Ping": lambda a: {}}, secret="t")
    srv.start()
    try:
        host = srv.address[4:].rpartition(":")[0]
        assert host not in ("0.0.0.0", "", "::")
        ok, _ = rpc.call(srv.address, "Ping", {}, secret="t")
        assert ok
    finally:
        srv.close()


def test_advertise_override(monkeypatch):
    monkeypatch.setenv("DSI_MR_ADVERTISE", "coord.example.net")
    srv = rpc.RpcServer("tcp:0.0.0.0:0", {"Ping": lambda a: {}}, secret="t")
    try:
        assert srv.address.startswith("tcp:coord.example.net:")
    finally:
        srv.close()


def test_tcp_wildcard_without_secret_refused(monkeypatch):
    """An open TCP listener accepts task-completion reports, so binding a
    non-loopback interface without DSI_MR_SECRET must fail loudly."""
    monkeypatch.delenv("DSI_MR_SECRET", raising=False)
    with pytest.raises(ValueError, match="DSI_MR_SECRET"):
        rpc.RpcServer("tcp:0.0.0.0:0", {"Ping": lambda a: {}})


def test_auth_token_enforced(tmp_path):
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Ping": lambda a: {"pong": 1}}, secret="hunter2")
    srv.start()
    try:
        # A rejected token is LOUD (AuthError), not a silent not-ok: a
        # misconfigured worker must not exit looking like end-of-job.
        with pytest.raises(rpc.AuthError):
            rpc.call(sock, "Ping", {}, secret="")  # no token
        with pytest.raises(rpc.AuthError):
            rpc.call(sock, "Ping", {}, secret="wrong")
        ok, reply = rpc.call(sock, "Ping", {}, secret="hunter2")
        assert ok and reply == {"pong": 1}
    finally:
        srv.close()


def test_auth_non_ascii_secret(tmp_path):
    """compare_digest(str, str) TypeErrors on non-ASCII; the comparison must
    be over utf-8 bytes so a passphrase secret can't crash the handler."""
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Ping": lambda a: {}}, secret="pässwörd")
    srv.start()
    try:
        ok, _ = rpc.call(sock, "Ping", {}, secret="pässwörd")
        assert ok
        with pytest.raises(rpc.AuthError):
            rpc.call(sock, "Ping", {}, secret="pässwörd2")
    finally:
        srv.close()


def test_auth_secret_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("DSI_MR_SECRET", "s3cret")
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Ping": lambda a: {}})  # picks up env
    srv.start()
    try:
        ok, _ = rpc.call(sock, "Ping", {})  # client picks up env too
        assert ok
        with pytest.raises(rpc.AuthError):
            rpc.call(sock, "Ping", {}, secret="wrong")
    finally:
        srv.close()


def test_auth_replay_rejected(tmp_path):
    """A captured authenticated frame re-sent verbatim must be rejected
    (VERDICT r2 weakness #6): the nonce is single-use inside the window."""
    import socket as _socket

    sock = str(tmp_path / "s")
    hits = []
    srv = rpc.RpcServer(sock, {"Ping": lambda a: hits.append(1) or {}},
                        secret="hunter2")
    srv.start()
    try:
        # Build one valid frame by hand, then send the identical bytes twice.
        body = rpc._canonical_body("Ping", {})
        nonce = "aa" * 16
        ts = repr(__import__("time").time())
        frame = {"method": "Ping", "args": {},
                 "auth": {"nonce": nonce, "ts": ts,
                          "mac": rpc._auth_mac("hunter2", nonce, ts, body)}}

        def send_raw():
            s = _socket.socket(_socket.AF_UNIX)
            s.connect(sock)
            try:
                rpc._send_frame(s, frame)
                return rpc._recv_frame(s)
            finally:
                s.close()

        first = send_raw()
        assert first["ok"] and hits == [1]
        replay = send_raw()
        assert not replay["ok"] and replay["error"] == "auth failed"
        assert hits == [1]  # the handler never ran for the replay
    finally:
        srv.close()


def test_auth_stale_timestamp_rejected(tmp_path):
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Ping": lambda a: {}}, secret="hunter2")
    srv.start()
    try:
        import socket as _socket
        import time as _time

        body = rpc._canonical_body("Ping", {})
        nonce = "bb" * 16
        ts = repr(_time.time() - 3600)  # far outside the 300 s window
        frame = {"method": "Ping", "args": {},
                 "auth": {"nonce": nonce, "ts": ts,
                          "mac": rpc._auth_mac("hunter2", nonce, ts, body)}}
        s = _socket.socket(_socket.AF_UNIX)
        s.connect(sock)
        try:
            rpc._send_frame(s, frame)
            resp = rpc._recv_frame(s)
        finally:
            s.close()
        assert not resp["ok"] and resp["error"] == "auth failed"
    finally:
        srv.close()


def test_dial_retry_survives_late_listener(tmp_path):
    """A transient ECONNREFUSED (listener mid-restart) must be retried, not
    mistaken for a dead coordinator — losing a worker to a transient dial
    error silently shrinks the fleet (VERDICT r1 weakness #2)."""
    import socket as _socket
    import threading as _threading

    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    addr = f"tcp:127.0.0.1:{port}"
    holder = {}

    def late_start():
        import time as _time
        _time.sleep(0.2)
        holder["srv"] = rpc.RpcServer(addr, {"Ping": lambda a: {"ok": 1}})
        holder["srv"].start()

    t = _threading.Thread(target=late_start)
    t.start()
    try:
        # One outer retry: on a heavily loaded box the late_start thread can
        # itself be delayed past the ~1.6 s dial-retry budget; the property
        # under test is that call() rides out ECONNREFUSED, not the exact
        # size of the budget.
        try:
            ok, reply = rpc.call(addr, "Ping", {})
        except rpc.CoordinatorGone:
            t.join()
            ok, reply = rpc.call(addr, "Ping", {})
        assert ok and reply == {"ok": 1}
    finally:
        t.join()
        srv = holder.get("srv")
        if srv is not None:
            srv.close()


def test_high_contention_soak(tmp_path):
    """32 threads x 50 dial-per-call RPCs against one server: with the Go-
    parity 128 listener backlog and transient-dial retry, not one call may
    die with CoordinatorGone (the round-1 stress test tripped exactly this
    with backlog 5 and no retry)."""
    import threading

    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Inc": lambda a: {"v": a["v"] + 1}})
    srv.start()
    errs: list = []

    def hammer(tid):
        try:
            for i in range(50):
                ok, r = rpc.call(sock, "Inc", {"v": i})
                if not ok or r["v"] != i + 1:
                    errs.append((tid, i, "bad reply"))
        except Exception as e:  # noqa: BLE001 — any escape is the failure
            errs.append((tid, repr(e)))

    try:
        ts = [threading.Thread(target=hammer, args=(t,)) for t in range(32)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs[:5]
    finally:
        srv.close()


def test_silent_peer_does_not_pin_handler_threads():
    """A connected-but-mute TCP peer must be timed out server-side."""
    import socket as _socket
    import threading as _threading

    srv = rpc.RpcServer("tcp:127.0.0.1:0", {"Ping": lambda a: {}})
    srv.start()
    try:
        mute = _socket.create_connection(
            tuple(rpc.parse_address(srv.address)[1]))
        # server still serves real clients while the mute peer idles
        ok, _ = rpc.call(srv.address, "Ping", {})
        assert ok
        mute.close()
        before = _threading.active_count()
        assert before < 50  # no thread pile-up
    finally:
        srv.close()


def test_non_object_response_frame_is_rpc_failure():
    """A server answering with a JSON array (corrupt or hostile) must yield
    (False, None) — the reference's ok=false path (worker.go:186-188) — not
    an AttributeError that kills the worker loop."""
    import socket
    import struct
    import threading

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve_one():
        conn, _ = srv.accept()
        with conn:
            payload = b"[1, 2, 3]"
            conn.recv(1 << 16)  # drain the request
            conn.sendall(struct.pack(">I", len(payload)) + payload)

    t = threading.Thread(target=serve_one, daemon=True)
    t.start()
    try:
        ok, reply = rpc.call(f"tcp:127.0.0.1:{port}", "Echo", {})
        assert (ok, reply) == (False, None)
    finally:
        t.join(timeout=5)
        srv.close()
