"""RPC layer: framed-JSON over Unix socket, dial-per-call semantics."""

import os

import pytest

from dsi_tpu.mr import rpc


def test_roundtrip(tmp_path):
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Echo": lambda a: {"got": a}})
    srv.start()
    try:
        ok, reply = rpc.call(sock, "Echo", {"x": 1})
        assert ok and reply == {"got": {"x": 1}}
    finally:
        srv.close()


def test_unknown_method_returns_not_ok(tmp_path):
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {})
    srv.start()
    try:
        ok, reply = rpc.call(sock, "Nope", {})
        assert not ok and reply is None
    finally:
        srv.close()


def test_dial_failure_raises_coordinator_gone(tmp_path):
    # Reference worker log.Fatals when the coordinator socket is gone
    # (mr/worker.go:176-178); we surface it as an exception the loop
    # treats as job-over.
    with pytest.raises(rpc.CoordinatorGone):
        rpc.call(str(tmp_path / "missing"), "X", {})


def test_stale_socket_file_is_replaced(tmp_path):
    sock = str(tmp_path / "s")
    open(sock, "w").close()  # stale file; server must os.remove it first
    srv = rpc.RpcServer(sock, {"M": lambda a: {}})
    srv.start()
    try:
        ok, _ = rpc.call(sock, "M", {})
        assert ok
    finally:
        srv.close()


def test_concurrent_calls(tmp_path):
    import threading
    sock = str(tmp_path / "s")
    srv = rpc.RpcServer(sock, {"Inc": lambda a: {"v": a["v"] + 1}})
    srv.start()
    errs = []

    def hit(i):
        ok, r = rpc.call(sock, "Inc", {"v": i})
        if not ok or r["v"] != i + 1:
            errs.append(i)

    try:
        ts = [threading.Thread(target=hit, args=(i,)) for i in range(32)]
        for t in ts: t.start()
        for t in ts: t.join()
        assert not errs
    finally:
        srv.close()
