"""Test configuration.

Tests never require TPU hardware: JAX-dependent tests run on a virtual
8-device CPU mesh (the multi-chip sharding path is validated the same way the
driver's dryrun does).  These env vars must be set before the first
``import jax`` anywhere in the test process.
"""

import os
import sys

# Force CPU even when the ambient environment points JAX at a TPU: the test
# suite validates logic and sharding on an 8-device virtual mesh; real-TPU
# runs happen via bench.py.  DSI_TEST_PLATFORM overrides for TPU smoke runs.
# The env var alone is not enough when a sitecustomize pre-registers a TPU
# plugin, so also pin the platform through jax.config before backends init.
_platform = os.environ.get("DSI_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Keep test-run AOT executables out of the repo's persistent cache (they are
# tiny CPU-platform entries; the repo cache is for the chip).
if "DSI_AOT_CACHE_DIR" not in os.environ:
    import atexit
    import shutil
    import tempfile

    _aot_tmp = tempfile.mkdtemp(prefix="dsi-aot-test-")
    os.environ["DSI_AOT_CACHE_DIR"] = _aot_tmp
    atexit.register(shutil.rmtree, _aot_tmp, True)

try:
    import jax

    jax.config.update("jax_platforms", _platform)
except ImportError:
    pass
