"""Test configuration.

Tests never require TPU hardware: JAX-dependent tests run on a virtual
8-device CPU mesh (the multi-chip sharding path is validated the same way the
driver's dryrun does).  These env vars must be set before the first
``import jax`` anywhere in the test process.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
