"""FNV-32a partitioner parity.

The partitioner must match the Go reference bit-for-bit
(``mr/worker.go:33-37``: fnv.New32a, then ``& 0x7fffffff``) or partition
contents differ from the spec (SURVEY.md §7 step 4).
"""

from dsi_tpu.mr.worker import fnv32a, ihash

# Published FNV-1a 32-bit vectors (same values Go's hash/fnv produces).
KNOWN = {
    b"": 0x811C9DC5,
    b"a": 0xE40C292C,
    b"b": 0xE70C2DE5,
    b"foobar": 0xBF9CF968,
}


def test_fnv32a_known_vectors():
    for data, want in KNOWN.items():
        assert fnv32a(data) == want, data


def test_ihash_masks_sign_bit():
    for key in ("", "a", "foobar", "the", "Zebra"):
        assert ihash(key) == fnv32a(key.encode()) & 0x7FFFFFFF
        assert 0 <= ihash(key) < 2**31


def test_partition_stability():
    # Partition assignment is a pure function of the key: same key always
    # lands in the same reduce bucket regardless of which map task emits it.
    for key in ("alpha", "beta", "gamma"):
        assert ihash(key) % 10 == ihash(key) % 10
