"""Serving-daemon tests (``dsi_tpu/serve``).

The daemon's contract, pinned end to end:

* K concurrent tenants pack into shared device steps and each tenant's
  output is byte-identical to the sequential oracle (the acceptance
  bar: >= 8 tenants);
* eviction (max-resident pressure + step quota) parks tenants on their
  delta-checkpoint chains and resumes them with exact results,
  ``resume_gap_s`` accounted per tenant;
* a REAL ``os._exit`` daemon kill mid-job (the fault-injection points
  ride the packer) resumes every in-flight tenant from its chain on
  restart with byte-identical output — via the actual ``mrserve``/
  ``mrsubmit`` CLIs in subprocesses;
* boot hygiene reaps ``.tmp-*`` orphans and GCs aged dead chains while
  never touching a live tenant's chain;
* the ``/statusz`` tenant section and ``dsi_serve_*`` metrics render.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.serve import client
from dsi_tpu.serve.daemon import ServeDaemon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def short_sock() -> str:
    # AF_UNIX paths cap at ~108 bytes; pytest tmp dirs can exceed it.
    return os.path.join(tempfile.mkdtemp(prefix="dsi-sv-"), "s.sock")


def make_corpus(path, tenant_tag, words=3000, seed=0):
    toks = [f"{tenant_tag}w{(seed * 31 + j) % 223:03d}" for j in range(words)]
    with open(path, "w") as f:
        f.write(" ".join(toks) + "\n")
    return path


def oracle_lines(files):
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential

    out = files[0] + ".oracle"
    run_sequential(wc.Map, wc.Reduce, files, out)
    with open(out, encoding="utf-8") as f:
        return sorted(l for l in f if l.strip())


def daemon_out_lines(out_dir, n_reduce=10):
    got = []
    for r in range(n_reduce):
        with open(os.path.join(out_dir, f"mr-out-{r}"),
                  encoding="utf-8") as f:
            got.extend(l for l in f if l.strip())
    return sorted(got)


def test_daemon_packs_eight_tenants_with_parity(tmp_path):
    """The acceptance bar: 8 concurrent small jobs, per-tenant byte
    parity vs the sequential oracle, and the packing evidence — more
    rows than dispatches, multiple tenants per step."""
    spool = str(tmp_path / "spool")
    jobs = []
    for i in range(8):
        p = make_corpus(str(tmp_path / f"c{i}.txt"), f"t{i}", seed=i)
        jobs.append((f"tenant{i}", [p]))
    d = ServeDaemon(spool, socket_path=short_sock(), max_resident=8,
                    checkpoint_every=2, warm=False)
    # Enqueue BEFORE the scheduler starts so the first packed step sees
    # every tenant (deterministic packing evidence).
    reps = [d._rpc_submit({"tenant": t, "app": "wc", "files": fs})
            for t, fs in jobs]
    assert all("job_id" in r for r in reps)
    d.start()
    try:
        client.wait_ready(d.socket_path, timeout=120)
        final = client.wait(d.socket_path,
                            [r["job_id"] for r in reps], timeout=180)
        assert all(j["state"] == "done" for j in final.values()), final
        for (tenant, files), rep in zip(jobs, reps):
            assert daemon_out_lines(rep["out_dir"]) == \
                oracle_lines(files), tenant
        st = d.packer.stats
        assert st["packed_rows"] > st["packed_steps"] >= 1
        assert st["max_tenants_per_step"] >= 2
        # The statusz tenant section + metrics series render.
        text = d._statusz_section()
        assert "tenant=tenant0" in text and "packed_steps=" in text
        metrics = d._metrics_section()
        assert 'dsi_serve_tenant_steps{tenant="tenant0"}' in metrics
        assert "dsi_serve_packed_steps" in metrics
        # And ride the live-telemetry plane's /statusz renderer.
        from dsi_tpu.obs.live import LiveTelemetry

        page = LiveTelemetry().statusz_text()
        assert "-- serve tenants --" in page
        assert "tenant=tenant0" in page
    finally:
        d.close()


def test_eviction_quota_parks_and_resumes(tmp_path):
    """max_resident=2 + a 1-step quota over 4 multi-step tenants forces
    evict → park-on-chain → resume cycles; results stay exact and the
    per-tenant eviction/resume accounting is visible."""
    spool = str(tmp_path / "spool")
    jobs = []
    for i in range(4):
        p = make_corpus(str(tmp_path / f"c{i}.txt"), f"e{i}",
                        words=4000, seed=i)
        jobs.append((f"ev{i}", [p]))
    d = ServeDaemon(spool, socket_path=short_sock(), max_resident=2,
                    quota_steps=1, chunk_bytes=1 << 10,
                    checkpoint_every=1, warm=False)
    reps = [d._rpc_submit({"tenant": t, "app": "wc", "files": fs})
            for t, fs in jobs]
    d.start()
    try:
        client.wait_ready(d.socket_path, timeout=120)
        final = client.wait(d.socket_path,
                            [r["job_id"] for r in reps], timeout=240)
        assert all(j["state"] == "done" for j in final.values()), final
        for (tenant, files), rep in zip(jobs, reps):
            assert daemon_out_lines(rep["out_dir"]) == \
                oracle_lines(files), tenant
        tenants = client.status(d.socket_path)["tenants"]
        assert sum(s["evictions"] for s in tenants.values()) >= 1
        assert sum(s["resumes"] for s in tenants.values()) >= 1
        assert any(s["resume_gap_s"] > 0 for s in tenants.values())
    finally:
        d.close()


def test_boot_hygiene_reaps_tmp_and_gcs_aged_chains(tmp_path):
    spool = str(tmp_path / "spool")
    jobs_dir = os.path.join(spool, "jobs")
    tenants_dir = os.path.join(spool, "tenants")
    os.makedirs(jobs_dir)
    os.makedirs(os.path.join(tenants_dir, "old", "dead-000001"))
    os.makedirs(os.path.join(tenants_dir, "live", "alive-000002"))
    # Orphans a crashed writer would leave.
    for p in (os.path.join(spool, ".tmp-orphan"),
              os.path.join(jobs_dir, ".tmp-j"),
              os.path.join(tenants_dir, "old", "dead-000001",
                           ".tmp-state")):
        with open(p, "w") as f:
            f.write("junk")
    # An aged dead chain vs a live (queued) tenant's chain.
    old_dir = os.path.join(tenants_dir, "old", "dead-000001")
    with open(os.path.join(old_dir, "manifest-000001.json"), "w") as f:
        f.write("{}")
    past = time.time() - 40 * 86400
    os.utime(os.path.join(old_dir, "manifest-000001.json"), (past, past))
    live_dir = os.path.join(tenants_dir, "live", "alive-000002")
    with open(os.path.join(live_dir, "manifest-000001.json"), "w") as f:
        f.write("{}")
    os.utime(os.path.join(live_dir, "manifest-000001.json"),
             (past, past))
    from dsi_tpu.utils.atomicio import write_bytes_durable

    job = {"job_id": "alive-000002", "tenant": "live", "app": "wc",
           "files": ["/nonexistent"], "n_reduce": 10,
           "out_dir": os.path.join(spool, "out", "alive-000002"),
           "pattern": None, "state": "running",
           "submitted_ts": 0, "error": None, "stats": {}}
    write_bytes_durable(os.path.join(jobs_dir, "alive-000002.json"),
                        json.dumps(job).encode())
    d = ServeDaemon(spool, socket_path=short_sock(), warm=False)
    # Never started: hygiene runs at construction.
    assert d.boot_reaped >= 3
    assert not os.path.exists(os.path.join(spool, ".tmp-orphan"))
    assert not os.path.exists(old_dir)          # aged dead chain: gone
    assert os.path.exists(live_dir)             # live chain: untouched
    assert d.boot_gc_chains >= 1
    d._rpc.close()


def test_daemon_kill9_resumes_two_inflight_tenants(tmp_path):
    """The crash contract, with a REAL ``os._exit`` (fault injection in
    the packer's mid-fold) through the actual CLIs: two tenants
    in flight, daemon dies mid-packed-step, a restarted daemon resumes
    both from their chains, and both outputs byte-compare equal to the
    sequential oracle."""
    spool = str(tmp_path / "spool")
    sock = short_sock()
    corpora = []
    for i in range(2):
        p = make_corpus(str(tmp_path / f"k{i}.txt"), f"k{i}",
                        words=14000, seed=i)
        corpora.append(p)
    env = dict(os.environ)
    env.update({"DSI_FAULT_POINT": "mid-fold", "DSI_FAULT_STEP": "3"})
    args = [sys.executable, "-m", "dsi_tpu.cli.mrserve",
            "--spool", spool, "--socket", sock, "--chunk-bytes", "1024",
            "--checkpoint-every", "1", "--no-warm"]
    proc = subprocess.Popen(args, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    jids = []
    try:
        client.wait_ready(sock, timeout=120)
        for i, p in enumerate(corpora):
            out = subprocess.run(
                [sys.executable, "-m", "dsi_tpu.cli.mrsubmit",
                 "--socket", sock, "--tenant", f"kt{i}", p],
                capture_output=True, text=True, cwd=REPO, timeout=60)
            assert out.returncode == 0, out.stderr
            jids.append(json.loads(out.stdout.strip().splitlines()[0]))
        rc = proc.wait(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == 87, (rc, proc.stderr.read() if proc.stderr else "")

    # Restart WITHOUT the fault: journaled jobs resume from chains.
    env2 = dict(os.environ)
    proc2 = subprocess.Popen(args, env=env2, cwd=REPO,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
    try:
        client.wait_ready(sock, timeout=120)
        final = client.wait(sock, [j["job_id"] for j in jids],
                            timeout=240)
        assert all(j["state"] == "done" for j in final.values()), final
        tenants = client.status(sock)["tenants"]
        for i in range(2):
            assert tenants[f"kt{i}"]["resumes"] >= 1, tenants
        for i, (p, rep) in enumerate(zip(corpora, jids)):
            assert daemon_out_lines(rep["out_dir"]) == \
                oracle_lines([p]), f"tenant kt{i} parity after kill -9"
        client.shutdown(sock)
        proc2.wait(timeout=60)
    finally:
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait()


def test_submit_validation_errors():
    d = ServeDaemon(tempfile.mkdtemp(prefix="dsi-sv-spool-"),
                    socket_path=short_sock(), warm=False)
    try:
        assert "error" in d._rpc_submit({"tenant": "t", "app": "nope",
                                         "files": ["/f"]})
        assert "error" in d._rpc_submit({"tenant": "t", "app": "wc",
                                         "files": []})
        assert "error" in d._rpc_submit({"tenant": "t", "app": "wc",
                                         "files": ["/no/such/file"]})
        assert "error" in d._rpc_submit({"tenant": "t", "app": "grep",
                                         "files": [__file__]})
        assert "error" in d._rpc_submit({"tenant": "t", "app": "wc",
                                         "files": [__file__],
                                         "n_reduce": 3})
    finally:
        d._rpc.close()
