"""Crash-resume parity for the checkpoint/restore subsystem (dsi_tpu/ckpt).

The contract under test is the strongest the engines can make: kill a
streaming engine at a named fault point (``DSI_FAULT_POINT``), resume
from the last durable checkpoint, and the FINAL output — word-count
table, grep histogram/top-k, indexer postings including per-word order
and df top-k — is bit-identical to an uninterrupted run.  The grid runs
in-process (``DSI_FAULT_MODE=raise``: the fault raises instead of
``os._exit`` so one interpreter can afford engine x fault-point x mode
cells inside the tier-1 budget); the CLI tests at the bottom use the
real thing — ``os._exit`` mid-engine in a subprocess, resume in a fresh
process — so the durable-write path is exercised by actual process
death, not a stand-in.
"""

import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.ckpt import (
    FAULT_EXIT,
    FAULT_POINTS,
    CheckpointMismatch,
    CheckpointPolicy,
    CheckpointStore,
    FaultInjected,
    checkpoint_every_default,
    reset_faults,
    skip_stream,
)
from dsi_tpu.parallel.grepstream import (
    grep_host_oracle,
    grep_streaming,
    indexer_streaming,
)
from dsi_tpu.parallel.shuffle import default_mesh
from dsi_tpu.parallel.streaming import wordcount_streaming
from dsi_tpu.parallel.tfidf import tfidf_sharded

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh():
    return default_mesh(4)


def _letters(i: int) -> str:
    return "".join(chr(97 + (i // 26 ** j) % 26) for j in range(3))


WC_WORDS = [_letters(i) for i in range(120)]
WC_TEXT = ((" ".join(WC_WORDS) + "\n") * 80).encode()  # ~38 KB, ~10 steps
WC_CHUNK = 1 << 10

_GREP_LINES = []
for _i in range(3000):
    _GREP_LINES.append(b"ab " * (_i % 5) + b"line" + str(_i).encode())
GREP_TEXT = b"\n".join(_GREP_LINES) + b"\n"  # ~45 KB, ~6 steps
GREP_CHUNK = 1 << 11

IDX_DOCS = [(" ".join(WC_WORDS[(3 * i) % 90:(3 * i) % 90 + 14])
             + " common words").encode() for i in range(20)]  # 5 waves

#: point -> which occurrence to kill at, tuned so a checkpoint exists
#: BEFORE the crash for every point (every=2): resume must restore real
#: state, not just start over.
_FAULT_AT = {"post-dispatch": 4, "mid-fold": 4, "pre-sync": 2,
             "post-ckpt": 2, "mid-capture": 2, "mid-commit": 2}

_BASE = {}


def _fault_env(monkeypatch, point, step):
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    monkeypatch.setenv("DSI_FAULT_POINT", point)
    monkeypatch.setenv("DSI_FAULT_STEP", str(step))


def _clear_fault(monkeypatch):
    for k in ("DSI_FAULT_MODE", "DSI_FAULT_POINT", "DSI_FAULT_STEP"):
        monkeypatch.delenv(k, raising=False)


def _run_wc(ckpt=None, resume=False, dacc=False, depth=2, stats=None,
            async_=None, delta=None):
    reset_faults()
    return wordcount_streaming(
        [WC_TEXT], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
        u_cap=256, depth=depth, device_accumulate=dacc, sync_every=2,
        checkpoint_dir=ckpt, checkpoint_every=2, checkpoint_async=async_,
        checkpoint_delta=delta, resume=resume, pipeline_stats=stats)


def _run_grep(ckpt=None, resume=False, dacc=False, depth=2, stats=None,
              async_=None, delta=None):
    reset_faults()
    return grep_streaming(
        [GREP_TEXT], "ab", mesh=_mesh(), chunk_bytes=GREP_CHUNK,
        depth=depth, device_accumulate=dacc, sync_every=2, topk=8,
        checkpoint_dir=ckpt, checkpoint_every=2, checkpoint_async=async_,
        checkpoint_delta=delta, resume=resume, pipeline_stats=stats)


def _run_idx(ckpt=None, resume=False, dacc=False, depth=2, stats=None,
             async_=None, delta=None):
    reset_faults()
    return indexer_streaming(
        IDX_DOCS, mesh=_mesh(), n_reduce=10, u_cap=1 << 9, depth=depth,
        device_accumulate=dacc, sync_every=2, topk=8,
        checkpoint_dir=ckpt, checkpoint_every=2, checkpoint_async=async_,
        checkpoint_delta=delta, resume=resume, stats=stats)


_RUNNERS = {"wc": _run_wc, "grep": _run_grep, "idx": _run_idx}


def _baseline(engine, dacc):
    key = (engine, dacc)
    if key not in _BASE:
        _BASE[key] = _RUNNERS[engine](dacc=dacc)
        assert _BASE[key] is not None
    return _BASE[key]


def _crash_resume(engine, monkeypatch, tmp_path, point, dacc, depth=2,
                  async_=None, delta=None):
    """Run with a fault armed (expect it to fire), then resume and
    return the resumed result."""
    run = _RUNNERS[engine]
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, point, _FAULT_AT[point])
    with pytest.raises(FaultInjected):
        run(ckpt=ck, dacc=dacc, depth=depth, async_=async_, delta=delta)
    _clear_fault(monkeypatch)
    stats = {}
    res = run(ckpt=ck, resume=True, dacc=dacc, depth=depth, stats=stats,
              async_=async_, delta=delta)
    return res, stats


# ── the crash-resume parity grid ───────────────────────────────────────


@pytest.mark.parametrize("dacc", [False, True])
@pytest.mark.parametrize("point", FAULT_POINTS)
def test_wc_crash_resume_parity(monkeypatch, tmp_path, point, dacc):
    if point == "pre-sync" and not dacc:
        pytest.skip("pre-sync exists only on the device-accumulate path")
    res, stats = _crash_resume("wc", monkeypatch, tmp_path, point, dacc)
    assert res == _baseline("wc", dacc)
    if point in ("post-ckpt", "mid-fold"):
        # A checkpoint provably existed before the crash: the resume
        # must have restored it (sought past the cursor), not replayed
        # the stream from byte 0.
        assert stats["resume_cursor"] > 0


@pytest.mark.parametrize("dacc", [False, True])
@pytest.mark.parametrize("point", FAULT_POINTS)
def test_grep_crash_resume_parity(monkeypatch, tmp_path, point, dacc):
    if point == "pre-sync" and not dacc:
        pytest.skip("pre-sync exists only on the device-accumulate path")
    res, stats = _crash_resume("grep", monkeypatch, tmp_path, point, dacc)
    assert res == _baseline("grep", dacc)
    assert res == grep_host_oracle([GREP_TEXT], "ab", topk=8)


@pytest.mark.parametrize("dacc", [False, True])
@pytest.mark.parametrize("point", FAULT_POINTS)
def test_indexer_crash_resume_parity(monkeypatch, tmp_path, point, dacc):
    if point == "pre-sync" and not dacc:
        pytest.skip("pre-sync exists only on the device-accumulate path")
    res, stats = _crash_resume("idx", monkeypatch, tmp_path, point, dacc)
    base = _baseline("idx", dacc)
    # Postings equality includes per-word doc order; topk includes df
    # count ties broken by word.
    assert res == base


# ── async + incremental (ISSUE 8): the capture/commit split under fire ──


@pytest.mark.parametrize("engine", ["wc", "grep", "idx"])
@pytest.mark.parametrize("point", ("mid-capture", "mid-commit",
                                   "mid-fold"))
def test_async_delta_crash_resume_parity(monkeypatch, tmp_path, engine,
                                         point):
    """The async overlapped + incremental mode under the same bar as
    PR 5's sync path: kill during a capture, during a background
    commit, or at the torn-update instant, resume from whatever chain
    survived, and the final output is bit-identical.  A death
    mid-commit means the in-flight delta/image never produced a
    manifest — the previous complete chain must win."""
    res, stats = _crash_resume(engine, monkeypatch, tmp_path, point,
                               dacc=True, async_=True, delta=True)
    assert res == _baseline(engine, True)


@pytest.mark.parametrize("dacc", [False, True])
def test_wc_async_delta_host_and_device_paths(monkeypatch, tmp_path,
                                              dacc):
    res, stats = _crash_resume("wc", monkeypatch, tmp_path, "post-ckpt",
                               dacc=dacc, async_=True, delta=True)
    assert res == _baseline("wc", dacc)
    assert stats["resume_cursor"] > 0


@pytest.mark.parametrize("engine", ["grep", "idx"])
def test_async_delta_host_path_crash_resume(monkeypatch, tmp_path,
                                            engine):
    """The non-dacc delta spellings (grep's cand_mark watermark +
    newest-wins hist/totals, the indexer's HostDeltaLog wave rows)
    under a crash mid-chain — the device-path grid above never touches
    them."""
    res, stats = _crash_resume(engine, monkeypatch, tmp_path,
                               "mid-fold", dacc=False, async_=True,
                               delta=True)
    assert res == _baseline(engine, False)


def test_tfidf_async_delta_crash_resume_parity(monkeypatch, tmp_path):
    """The TF-IDF wave walk's async+delta chain (DevicePostings
    take_delta in dacc mode) across a mid-fold crash."""
    docs = IDX_DOCS
    base = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9)
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, "mid-fold", 4)
    reset_faults()
    with pytest.raises(FaultInjected):
        tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                      device_accumulate=True, sync_every=2,
                      checkpoint_dir=ck, checkpoint_every=1,
                      checkpoint_async=True, checkpoint_delta=True)
    _clear_fault(monkeypatch)
    reset_faults()
    stats = {}
    res = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                        device_accumulate=True, sync_every=2,
                        checkpoint_dir=ck, checkpoint_every=1,
                        checkpoint_async=True, checkpoint_delta=True,
                        resume=True, wave_stats=stats)
    assert res == base


def test_wc_delta_rebase_cadence_and_counters(tmp_path, monkeypatch):
    """Cadence-1 deltas with the default re-base window: the save
    counters decompose exactly (first save full, a full re-base every
    DSI_STREAM_CKPT_REBASE deltas), payload byte totals land in the
    stats, and the chain restores bit-identically."""
    monkeypatch.setenv("DSI_STREAM_CKPT_REBASE", "4")
    ck = str(tmp_path / "ck")
    stats = {}
    res = wordcount_streaming(
        [WC_TEXT], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
        u_cap=256, depth=2, device_accumulate=True, sync_every=2,
        checkpoint_dir=ck, checkpoint_every=1, checkpoint_async=True,
        checkpoint_delta=True, pipeline_stats=stats)
    assert res == _baseline("wc", True)
    saves, deltas = stats["ckpt_saves"], stats["ckpt_deltas"]
    assert saves >= 5 and 0 < deltas < saves
    # First save full, then <=4 deltas per full (the rebase window).
    fulls = saves - deltas
    assert fulls >= (saves + 4) // 5
    assert stats["ckpt_full_bytes"] > 0 and stats["ckpt_delta_bytes"] > 0
    # Append-heavy dacc stream: a delta is strictly smaller per save
    # than a full image.
    assert (stats["ckpt_delta_bytes"] / deltas
            < stats["ckpt_full_bytes"] / fulls)
    res2 = wordcount_streaming(
        [WC_TEXT], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
        u_cap=256, depth=2, device_accumulate=True, sync_every=2,
        checkpoint_dir=ck, checkpoint_every=1, checkpoint_async=True,
        checkpoint_delta=True, resume=True)
    assert res2 == _baseline("wc", True)


def test_rebase_one_means_every_save_full(tmp_path, monkeypatch):
    """The documented knob edge: ``DSI_STREAM_CKPT_REBASE=1`` really is
    every-save-full — zero deltas, flat restores — even with
    ``--ckpt-delta`` on."""
    monkeypatch.setenv("DSI_STREAM_CKPT_REBASE", "1")
    ck = str(tmp_path / "ck")
    stats = {}
    res = wordcount_streaming(
        [WC_TEXT], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
        u_cap=256, depth=2, device_accumulate=True, sync_every=2,
        checkpoint_dir=ck, checkpoint_every=1, checkpoint_delta=True,
        pipeline_stats=stats)
    assert res == _baseline("wc", True)
    assert stats["ckpt_saves"] >= 5 and stats["ckpt_deltas"] == 0
    assert not any(n.startswith("delta-") for n in os.listdir(ck))


def test_commit_worker_single_in_flight_barrier():
    """The writer's documented barrier: with ``max_pending=1`` a second
    submit must BLOCK while the first thunk is still RUNNING (a bounded
    queue alone would admit one running + one queued)."""
    import threading
    import time as _time

    from dsi_tpu.parallel.pipeline import CommitWorker

    w = CommitWorker(name="t-cw")
    release = threading.Event()
    running = threading.Event()

    def slow():
        running.set()
        release.wait(5.0)

    assert w.submit(slow) == 0.0
    running.wait(5.0)
    t0 = _time.perf_counter()
    done2 = []

    def second():
        done2.append(_time.perf_counter())

    def unblock():
        _time.sleep(0.15)
        release.set()

    threading.Thread(target=unblock, daemon=True).start()
    waited = w.submit(second)  # must block until slow() finishes
    assert waited >= 0.1, waited
    assert w.drain() >= 0.0
    assert done2
    w.shutdown()


def test_wc_delta_resume_across_forced_widen(monkeypatch, tmp_path):
    """A device-table widen straddling a delta chain: the forced tiny
    rung widens mid-stream (drain into the host accumulator + realloc),
    delta saves land around it, the crash loses the tail, and the chain
    restore (base drained + deltas re-applied) must still reproduce the
    uninterrupted output bit-identically."""
    monkeypatch.setenv("DSI_DEVICE_TABLE_CAP", "16")
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, "mid-fold", 6)
    stats = {}
    with pytest.raises(FaultInjected):
        _run_wc(ckpt=ck, dacc=True, stats=stats, async_=True, delta=True)
    assert stats.get("widens", 0) >= 1
    _clear_fault(monkeypatch)
    res = _run_wc(ckpt=ck, resume=True, dacc=True, async_=True,
                  delta=True)
    assert res == _baseline("wc", True)


def test_wc_delta_chain_resume_across_mesh_degrees(monkeypatch,
                                                   tmp_path):
    """A ``--mesh-shards`` degree change straddling a delta chain: the
    chain was saved by a mesh-sharded run, the resume runs host-merge
    (degree 0).  The chain restore already re-enters through the drain
    path, so the degree change rides the same machinery — output stays
    bit-identical."""
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, "mid-fold", 6)
    with pytest.raises(FaultInjected):
        reset_faults()
        wordcount_streaming(
            [WC_TEXT], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
            u_cap=256, depth=2, device_accumulate=True, sync_every=2,
            mesh_shards=2, checkpoint_dir=ck, checkpoint_every=1,
            checkpoint_async=True, checkpoint_delta=True)
    _clear_fault(monkeypatch)
    reset_faults()
    stats = {}
    res = wordcount_streaming(
        [WC_TEXT], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
        u_cap=256, depth=2, device_accumulate=True, sync_every=2,
        mesh_shards=0, checkpoint_dir=ck, checkpoint_every=1,
        checkpoint_async=True, checkpoint_delta=True, resume=True,
        pipeline_stats=stats)
    assert res == _baseline("wc", True)
    assert "resharded_resume" in stats and stats["resharded_resume"] == 2


@pytest.mark.parametrize("point", ["mid-fold", "post-ckpt"])
def test_wc_crash_resume_parity_with_reader_pool(monkeypatch, tmp_path,
                                                 point):
    """Cursor exactness under the parallel ingest pool (ISSUE 13): a
    crash with readahead in flight — the pool has read blocks the
    batcher never consumed — must resume byte-identically from the
    durable cursor, even when the resume run uses a DIFFERENT reader
    count (batching is a pure function of the byte stream; the pool
    only changes scheduling)."""
    from dsi_tpu.utils.ioread import ParallelBlocks, serial_blocks

    half = len(WC_TEXT) // 2
    paths = []
    for i, piece in enumerate((WC_TEXT[:half], WC_TEXT[half:])):
        p = tmp_path / f"c{i}.txt"
        p.write_bytes(piece)
        paths.append(str(p))

    def pool_run(readers, **kw):
        reset_faults()
        # Small blocks so readahead is GENUINELY in flight at the crash
        # (several blocks resident in slots beyond the consumed cursor).
        return wordcount_streaming(
            ParallelBlocks(paths, block_bytes=2048, readers=readers),
            mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK, u_cap=256,
            sync_every=2, checkpoint_every=2, **kw)

    # Baseline over the SAME byte stream (the pool inserts the
    # stream_files file separator, so WC_TEXT alone is not it).
    reset_faults()
    baseline = wordcount_streaming(
        [b"".join(serial_blocks(paths))], mesh=_mesh(), n_reduce=10,
        chunk_bytes=WC_CHUNK, u_cap=256)
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, point, _FAULT_AT[point])
    with pytest.raises(FaultInjected):
        pool_run(3, checkpoint_dir=ck)
    _clear_fault(monkeypatch)
    stats: dict = {}
    res = pool_run(2, checkpoint_dir=ck, resume=True,
                   pipeline_stats=stats)
    assert res == baseline
    assert stats["resume_cursor"] > 0  # restored, not replayed from 0
    assert stats["ingest_readers"] == 2


@pytest.mark.parametrize("depth", [1, 3])
def test_wc_crash_resume_parity_across_depths(monkeypatch, tmp_path,
                                              depth):
    res, _ = _crash_resume("wc", monkeypatch, tmp_path, "mid-fold",
                           dacc=True, depth=depth)
    assert res == _baseline("wc", True)


def test_wc_resume_across_forced_widen(monkeypatch, tmp_path):
    """A device-table widen straddling a checkpoint: the tiny forced
    rung widens mid-stream (drain into the host accumulator + realloc),
    a checkpoint lands between widens, the crash loses the tail, and
    resume must reconstruct the widened table image exactly."""
    monkeypatch.setenv("DSI_DEVICE_TABLE_CAP", "16")
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, "mid-fold", 6)
    stats = {}
    with pytest.raises(FaultInjected):
        _run_wc(ckpt=ck, dacc=True, stats=stats)
    assert stats.get("widens", 0) >= 1  # the forced rung actually widened
    _clear_fault(monkeypatch)
    res = _run_wc(ckpt=ck, resume=True, dacc=True)
    assert res == _baseline("wc", True)


def test_grep_resume_across_forced_topk_widen(monkeypatch, tmp_path):
    monkeypatch.setenv("DSI_DEVICE_TOPK_CAP", "8")
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, "mid-fold", 6)
    stats = {}
    with pytest.raises(FaultInjected):
        _run_grep(ckpt=ck, dacc=True, stats=stats)
    assert stats.get("widens", 0) >= 1
    _clear_fault(monkeypatch)
    res = _run_grep(ckpt=ck, resume=True, dacc=True)
    assert res == _baseline("grep", True)


def test_tfidf_crash_resume_parity(monkeypatch, tmp_path):
    """The wave-cursor checkpoint on the TF-IDF walk (the indexer grid
    above exercises the same machinery more heavily)."""
    docs = IDX_DOCS
    base = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9)
    ck = str(tmp_path / "ck")
    _fault_env(monkeypatch, "mid-fold", 4)
    reset_faults()
    with pytest.raises(FaultInjected):
        tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                      device_accumulate=True, sync_every=2,
                      checkpoint_dir=ck, checkpoint_every=2)
    _clear_fault(monkeypatch)
    reset_faults()
    res = tfidf_sharded(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9,
                        device_accumulate=True, sync_every=2,
                        checkpoint_dir=ck, checkpoint_every=2, resume=True)
    assert res == base


def test_resume_skips_confirmed_work(monkeypatch, tmp_path):
    """Resume is a restore + tail replay, not a rerun: the resumed run
    processes strictly fewer steps than the whole stream holds."""
    full_stats = {}
    _run_wc(stats=full_stats)
    res, stats = _crash_resume("wc", monkeypatch, tmp_path, "post-ckpt",
                               dacc=False)
    assert res == _baseline("wc", False)
    assert stats["resume_cursor"] > 0
    assert stats["steps"] < full_stats["steps"]


# ── store / policy / plumbing units ────────────────────────────────────


def test_checkpoint_policy_cadence_and_env(monkeypatch):
    p = CheckpointPolicy(3)
    for _ in range(2):
        p.note_step()
        assert not p.due()
    p.note_step()
    assert p.due()
    p.reset()
    assert not p.due()
    monkeypatch.setenv("DSI_STREAM_CKPT_EVERY", "7")
    assert checkpoint_every_default() == 7
    assert checkpoint_every_default(2) == 2
    monkeypatch.setenv("DSI_STREAM_CKPT_EVERY", "junk")
    assert checkpoint_every_default() == 32


def test_checkpoint_policy_time_trigger(monkeypatch):
    p = CheckpointPolicy(1000, secs=0.01)
    p.note_step()
    import time

    time.sleep(0.02)
    assert p.due()
    p.reset()
    assert not p.due()  # no step since reset: time alone never fires


def test_store_roundtrip_gc_and_fallback(tmp_path):
    st = CheckpointStore(str(tmp_path), "wc", {"n_dev": 4})
    for i in range(3):
        st.save({"a": np.arange(i + 1)}, {"cursor": 10 * i})
    # Last-two retention: seqs 1 is gone, 2 and 3 remain.
    names = sorted(os.listdir(str(tmp_path)))
    assert "manifest-000001.json" not in names
    assert "manifest-000002.json" in names and "manifest-000003.json" in names
    meta, arrays = st.load_latest()
    assert meta["cursor"] == 20 and np.array_equal(arrays["a"],
                                                   np.arange(3))
    # Corrupt the newest payload: the loader must fall back to seq 2.
    p3 = str(tmp_path / "state-000003.npz")
    with open(p3, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    meta, arrays = st.load_latest()
    assert meta["cursor"] == 10 and np.array_equal(arrays["a"],
                                                   np.arange(2))


def test_store_chain_gc_protects_live_base(tmp_path):
    """Chain-aware GC (ISSUE 8): last-two retention must never reap a
    base ``state-<seq>.npz`` that a live delta chain still references —
    with three deltas chained on one base, both retained restore points
    are deltas, and naive last-two would have deleted the base they
    both need."""
    st = CheckpointStore(str(tmp_path), "wc", {})
    st.save({"a": np.arange(3)}, {"cursor": 0})                   # seq 1
    for i in range(3):                                            # 2..4
        st.save_delta({"d": np.arange(i + 1)}, {"cursor": 10 * (i + 1)})
    names = sorted(os.listdir(str(tmp_path)))
    assert "state-000001.npz" in names          # the live chain's base
    assert "manifest-000001.json" in names
    meta, arrays, deltas = st.load_latest_chain()
    assert meta["cursor"] == 0 and len(deltas) == 3
    assert [m["cursor"] for m, _ in deltas] == [10, 20, 30]
    # A NEW full save starts a fresh chain; once two newer restore
    # points exist without references into the old chain, it goes.
    st.save({"a": np.arange(9)}, {"cursor": 99})                  # seq 5
    st.save({"a": np.arange(9)}, {"cursor": 100})                 # seq 6
    names = sorted(os.listdir(str(tmp_path)))
    assert "state-000001.npz" not in names
    assert not any(n.startswith("delta-") for n in names)


def test_store_torn_chain_falls_back_to_complete_chain(tmp_path):
    """A torn middle delta invalidates every seq above it: the walk
    falls back to the last COMPLETE chain (ultimately the bare base),
    never restores around a hole."""
    st = CheckpointStore(str(tmp_path), "wc", {})
    st.save({"a": np.arange(2)}, {"cursor": 0})                   # seq 1
    st.save_delta({"d": np.arange(1)}, {"cursor": 10})            # seq 2
    st.save_delta({"d": np.arange(2)}, {"cursor": 20})            # seq 3
    st.save_delta({"d": np.arange(3)}, {"cursor": 30})            # seq 4
    # Corrupt the MIDDLE delta's payload: seqs 3 and 4 now both sit on
    # a hole; the loader must fall back to base+delta2.
    p = str(tmp_path / "delta-000003.npz")
    with open(p, "r+b") as f:
        f.seek(5)
        b = f.read(1)
        f.seek(5)
        f.write(bytes([b[0] ^ 0xFF]))
    meta, arrays, deltas = st.load_latest_chain()
    assert len(deltas) == 1 and deltas[0][0]["cursor"] == 10
    # Remove that delta entirely (missing middle): same fallback.
    os.remove(p)
    meta, arrays, deltas = st.load_latest_chain()
    assert len(deltas) == 1 and deltas[0][0]["cursor"] == 10
    # Now tear delta 2 as well: only the bare base survives.
    os.remove(str(tmp_path / "delta-000002.npz"))
    meta, arrays, deltas = st.load_latest_chain()
    assert deltas == [] and meta["cursor"] == 0
    # load_latest (full-only view) agrees with the chain walk's base.
    m2, _ = st.load_latest()
    assert m2["cursor"] == 0


def test_store_gc_retains_fallback_below_unreadable_link(tmp_path):
    """GC must err toward retention when a chain walk cannot reach its
    base: with a mid-chain manifest gone, later saves keep chaining
    above the hole — everything at or below it must survive GC, because
    the loader's fallback is exactly the complete chain down there."""
    st = CheckpointStore(str(tmp_path), "wc", {})
    st.save({"a": np.arange(2)}, {"cursor": 0})           # seq 1
    st.save_delta({"d": np.arange(1)}, {"cursor": 10})    # seq 2
    st.save_delta({"d": np.arange(2)}, {"cursor": 20})    # seq 3
    st.save_delta({"d": np.arange(3)}, {"cursor": 30})    # seq 4
    os.remove(str(tmp_path / "manifest-000003.json"))     # the hole
    st.save_delta({"d": np.arange(4)}, {"cursor": 40})    # seq 5
    st.save_delta({"d": np.arange(5)}, {"cursor": 50})    # seq 6
    names = os.listdir(str(tmp_path))
    assert "state-000001.npz" in names
    assert "delta-000002.npz" in names
    meta, arrays, deltas = st.load_latest_chain()
    assert meta["cursor"] == 0
    assert len(deltas) == 1 and deltas[0][0]["cursor"] == 10


def test_host_delta_log_trims_and_bounds_like_device_logs():
    """The host-merge delta log mirrors the device rule: entries are
    trimmed to the occupied prefix AND copied (an AOT-shaped pull is
    full capacity; a view would pin it), and a window past
    ``max_steps`` invalidates THIS window only — ``take()`` returns
    None (the full-save fallback) and the next window is clean."""
    from dsi_tpu.ckpt import HostDeltaLog

    log = HostDeltaLog(max_steps=2)
    big = np.arange(2 * 100 * 5, dtype=np.uint32).reshape(2, 100, 5)
    log.append(big, np.array([3, 7]))
    entries = log.take()
    assert len(entries) == 1
    rows, nus = entries[0]
    assert rows.shape == (2, 7, 5)  # trimmed to max(nus), not capacity
    assert rows.base is None        # a copy, not a view pinning `big`
    assert np.array_equal(rows, big[:, :7])
    assert log.take() == []         # re-armed, empty window
    for _ in range(3):              # overflow the 2-step window
        log.append(big, np.array([1, 1]))
    assert log.take() is None       # invalid -> full-save fallback
    log.append(big, np.array([2, 2]))
    assert len(log.take()) == 1     # next window valid again
    log.append(big, np.array([1, 1]))
    log.reset()                     # a full save landed
    assert log.take() == []


def test_store_delta_refuses_empty_lineage(tmp_path):
    st = CheckpointStore(str(tmp_path), "wc", {})
    with pytest.raises(RuntimeError):
        st.save_delta({"d": np.arange(1)}, {"cursor": 1})


def test_store_refuses_other_job_and_resets(tmp_path):
    st = CheckpointStore(str(tmp_path), "wc", {"chunk": 1024})
    st.save({"a": np.zeros(1)}, {"cursor": 1})
    other = CheckpointStore(str(tmp_path), "wc", {"chunk": 2048})
    with pytest.raises(CheckpointMismatch):
        other.load_latest()
    other.reset()
    assert st.load_latest() is None  # lineage gone
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith(("manifest-", "state-"))]


def test_store_torn_manifest_is_invisible(tmp_path):
    st = CheckpointStore(str(tmp_path), "wc", {})
    st.save({"a": np.ones(2)}, {"cursor": 5})
    # A manifest whose sidecar disagrees (torn write) must not load.
    st.save({"a": np.ones(3)}, {"cursor": 9})
    with open(str(tmp_path / "manifest-000002.json"), "ab") as f:
        f.write(b" ")
    meta, _ = st.load_latest()
    assert meta["cursor"] == 5


def test_skip_stream_seeks_exactly():
    blocks = [b"abc", b"", b"defg", b"hi"]
    assert b"".join(skip_stream(blocks, 0)) == b"abcdefghi"
    assert b"".join(skip_stream(blocks, 4)) == b"efghi"
    assert b"".join(skip_stream(blocks, 9)) == b""
    assert b"".join(skip_stream(blocks, 50)) == b""


def test_atomicio_durable_write_verify_and_reap(tmp_path):
    from dsi_tpu.utils.atomicio import (read_bytes_verified,
                                        reap_tmp_files,
                                        write_bytes_durable)

    p = str(tmp_path / "blob")
    crc = write_bytes_durable(p, b"hello world")
    assert os.path.exists(p + ".crc32")
    assert read_bytes_verified(p) == b"hello world"
    import zlib

    assert crc == zlib.crc32(b"hello world")
    with open(p, "ab") as f:  # tamper: sidecar now disagrees
        f.write(b"!")
    assert read_bytes_verified(p) is None
    assert read_bytes_verified(str(tmp_path / "absent")) is None
    open(str(tmp_path / ".tmp-orphan.x"), "w").close()
    assert reap_tmp_files(str(tmp_path)) == 1
    assert not os.path.exists(str(tmp_path / ".tmp-orphan.x"))


def test_fault_point_counts_per_point(monkeypatch):
    from dsi_tpu.ckpt import fault_point

    reset_faults()
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    monkeypatch.setenv("DSI_FAULT_POINT", "mid-fold")
    monkeypatch.setenv("DSI_FAULT_STEP", "2")
    fault_point("post-dispatch")  # other points never consume the budget
    fault_point("mid-fold")
    fault_point("post-dispatch")
    with pytest.raises(FaultInjected):
        fault_point("mid-fold")
    reset_faults()


def test_device_snapshot_roundtrip_byte_equal_drain(tmp_path):
    """Seeded-random snapshot round trip (the hypothesis twin lives in
    tests/test_property_fuzz.py and runs where hypothesis is
    installed): arbitrary service states, imaged by checkpoint_state,
    pushed through the real durable store, restored into a fresh
    service, must drain byte-equal."""
    from dsi_tpu.device import DeviceHistogram, DevicePostings, DeviceTable

    rng = np.random.default_rng(7)
    mesh = default_mesh(8)
    n_dev, cap, kk = 8, 8, 2

    class Capture:
        def __init__(self):
            self.rows = []

        def add(self, keys, lens, cnts, parts):
            self.rows.append((np.array(keys), np.array(lens),
                              np.array(cnts), np.array(parts)))

    for trial in range(4):
        nrows = rng.integers(0, cap + 1, n_dev)
        img = {"keys": rng.integers(0, 2 ** 32, (n_dev, cap, kk),
                                    dtype=np.uint32),
               "lens": rng.integers(0, 9, (n_dev, cap), dtype=np.int32),
               "cnts": rng.integers(0, 2 ** 63, (n_dev, cap)).astype(
                   np.uint64),
               "parts": rng.integers(0, 10, (n_dev, cap), dtype=np.int32),
               "tn": nrows.astype(np.int32), "nrows": nrows}
        store = CheckpointStore(str(tmp_path / f"t{trial}"), "fuzz", {})
        a1, a2 = Capture(), Capture()
        t1 = DeviceTable(mesh, kk=kk, cap=cap, acc=a1)
        t1.restore_state(img)
        store.save(t1.checkpoint_state(), {})
        _, arrays = store.load_latest()
        t2 = DeviceTable(mesh, kk=kk, cap=cap, acc=a2)
        t2.restore_state(arrays)
        t1.close()
        t2.close()
        assert len(a1.rows) == len(a2.rows)
        for ra, rb in zip(a1.rows, a2.rows):
            for x, y in zip(ra, rb):
                assert np.array_equal(x, y)

    # Postings buffer: random committed prefix, order must survive.
    width = kk + 4
    m = 5
    img = {"buf": rng.integers(0, 2 ** 32, (n_dev, m, width),
                               dtype=np.uint32),
           "nrows": rng.integers(0, m + 1, n_dev),
           "cap": np.array(cap, dtype=np.int64)}
    sink1, sink2 = [], []
    p1 = DevicePostings(mesh, width=width, cap=cap,
                        sink=lambda r: sink1.append(np.array(r)))
    p1.restore_state(img)
    st = p1.checkpoint_state()
    store = CheckpointStore(str(tmp_path / "pb"), "fuzz", {})
    store.save({"buf": st["buf"], "nrows": st["nrows"]},
               {"cap": int(st["cap"])})
    meta, arrays = store.load_latest()
    p2 = DevicePostings(mesh, width=width, cap=cap,
                        sink=lambda r: sink2.append(np.array(r)))
    p2.restore_state({"buf": arrays["buf"], "nrows": arrays["nrows"],
                      "cap": meta["cap"]})
    p1.close()
    p2.close()
    assert len(sink1) == len(sink2)
    assert all(np.array_equal(a, b) for a, b in zip(sink1, sink2))

    # Histogram vector.
    hstate = rng.integers(0, 2 ** 63, (n_dev, 6)).astype(np.uint64)
    h1 = DeviceHistogram(mesh, slots=6)
    h1.restore_state({"hist": hstate})
    store = CheckpointStore(str(tmp_path / "h"), "fuzz", {})
    store.save(h1.checkpoint_state(), {})
    _, arrays = store.load_latest()
    h2 = DeviceHistogram(mesh, slots=6)
    h2.restore_state(arrays)
    assert np.array_equal(h1.close(), h2.close())


# ── the real thing: process death + fresh-process resume ───────────────


def _cli_env(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.setdefault("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def test_cli_wcstream_real_crash_resume(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(WC_TEXT * 3)  # ~115 KB: ~7 steps at 16 KB/step
    env = _cli_env(tmp_path)
    ck = str(tmp_path / "ck")
    wd = str(tmp_path / "wd")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.wcstream", "--devices", "2",
           "--chunk-bytes", "8192", "--checkpoint-dir", ck,
           "--checkpoint-every", "1", "--workdir", wd, str(corpus)]
    env_crash = dict(env)
    env_crash.update({"DSI_FAULT_POINT": "mid-fold", "DSI_FAULT_STEP": "3"})
    p = subprocess.run(cmd, env=env_crash, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == FAULT_EXIT, p.stderr[-2000:]
    assert any(n.startswith("manifest-") for n in os.listdir(ck))
    p = subprocess.run(cmd + ["--resume", "--check"], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr


def test_cli_wcstream_async_delta_real_crash_resume(tmp_path):
    """REAL ``os._exit`` during an in-flight ASYNC snapshot
    (``mid-commit`` fires on the background writer thread after the
    capture materialized, before the store write): the half-captured
    save must be invisible — no torn manifest — and the fresh-process
    resume walks the surviving delta chain to bit-identical output."""
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(WC_TEXT * 3)
    env = _cli_env(tmp_path)
    ck = str(tmp_path / "ck")
    wd = str(tmp_path / "wd")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.wcstream", "--devices", "2",
           "--chunk-bytes", "8192", "--device-accumulate",
           "--sync-every", "2", "--checkpoint-dir", ck,
           "--checkpoint-every", "1", "--ckpt-async", "--ckpt-delta",
           "--workdir", wd, str(corpus)]
    env_crash = dict(env)
    env_crash.update({"DSI_FAULT_POINT": "mid-commit",
                      "DSI_FAULT_STEP": "3"})
    p = subprocess.run(cmd, env=env_crash, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == FAULT_EXIT, p.stderr[-2000:]
    names = os.listdir(ck)
    # Two commits landed before the third died mid-write: a base and a
    # delta chained on it survive, and nothing half-written is visible.
    assert any(n.startswith("state-") for n in names), names
    assert any(n.startswith("delta-") for n in names), names
    p = subprocess.run(cmd + ["--resume", "--check"], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr


@pytest.mark.slow
def test_cli_grepstream_real_crash_resume(tmp_path):
    corpus = tmp_path / "g.txt"
    corpus.write_bytes(GREP_TEXT * 4)
    env = _cli_env(tmp_path)
    ck = str(tmp_path / "ck")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.grepstream", "--devices",
           "2", "--pattern", "ab", "--chunk-bytes", "16384",
           "--device-accumulate", "--sync-every", "2",
           "--checkpoint-dir", ck, "--checkpoint-every", "1",
           str(corpus)]
    env_crash = dict(env)
    env_crash.update({"DSI_FAULT_POINT": "mid-fold", "DSI_FAULT_STEP": "3"})
    p = subprocess.run(cmd, env=env_crash, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == FAULT_EXIT, p.stderr[-2000:]
    p = subprocess.run(cmd + ["--resume", "--check"], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr
