"""Elastic-dataflow tests (ISSUE 16).

What they pin, per the elastic-executor contract:

* ``split_remaining`` sub-shard geometry: the ranges partition the
  shard's cursor range EXACTLY, every cut sits just after a ``\\n`` of
  the concatenated stream (the ``plan_shards`` token/line safety
  argument), the straggler's confirmed prefix becomes sub 0, and the
  PR-15 separator-at-range-end regression holds on sub-ranges too;
* the forced re-split state machine, driven through the coordinator's
  RPC handlers with no jax: trigger → journaled split → sub dispatch →
  per-sub first-commit-wins → shard resolves "split" (or the straggler
  outruns its own split and the subs are reaped) — duplicate commits
  stay 0 throughout, and the whole split state survives a journal
  replay;
* the pipelined plan executor: grep→wordcount overlap × stage-shards ×
  mesh stays bit-identical to the staged oracle, attributes a nonzero
  overlap wall, and crash-resumes from a fault injected mid-overlap;
* the two new stage kinds (grep→grep cascade, wordcount→top-k) match
  their staged twins.
"""

from __future__ import annotations

import json
import os
import re
import time

import pytest

from dsi_tpu.config import JobConfig
from dsi_tpu.mr import shards as sh
from dsi_tpu.mr.coordinator import Coordinator
from dsi_tpu.mr.types import TaskStatus


def write_corpus(path, lines=200, words=12, vocab=37):
    rows = []
    for i in range(lines):
        rows.append(" ".join(
            "w" + chr(ord("a") + (i * words + j) % vocab) * 3
            for j in range(words)))
    data = ("\n".join(rows) + "\n").encode()
    with open(path, "wb") as f:
        f.write(data)
    return data


# ── sub-shard geometry (pure functions, no jax) ───────────────────────


def _assert_partition(files, spec, ranges):
    """Ranges cover [spec.start, spec.end) exactly, in order, and every
    interior cut sits just after a newline of the concatenated stream."""
    total = sh.stream_total_bytes(files)
    whole = b"".join(sh.read_stream_range(files, 0, total))
    assert ranges[0][0] == spec.start
    assert ranges[-1][1] == spec.end
    for (s0, e0), (s1, e1) in zip(ranges, ranges[1:]):
        assert e0 == s1
        assert whole[s1 - 1:s1] == b"\n"  # token/line-safe cut
    got = b"".join(b"".join(sh.read_stream_range(files, s, e))
                   for s, e in ranges)
    assert got == whole[spec.start:spec.end]


def test_split_remaining_partitions_exactly(tmp_path):
    p1 = str(tmp_path / "a.txt")
    p2 = str(tmp_path / "b.txt")
    write_corpus(p1, lines=60)
    write_corpus(p2, lines=41)
    files = [p1, p2]
    spec = sh.plan_shards(files, 2)[1]  # nonzero start
    for cursor in (0, 1, 97, spec.size // 2):
        ranges = sh.split_remaining(files, spec, cursor, ways=3,
                                    min_bytes=64)
        assert ranges is not None, cursor
        _assert_partition(files, spec, ranges)
        # prefix sub iff the straggler had confirmed progress that
        # aligned past the shard start
        if cursor == 0:
            assert ranges[0] == (spec.start, ranges[0][1])
            assert len(ranges) == 3
        else:
            b0 = ranges[0][1] if ranges[0][0] == spec.start else None
            assert b0 is not None and b0 >= spec.start + cursor


def test_split_remaining_newline_alignment_at_split_point(tmp_path):
    p = str(tmp_path / "c.txt")
    write_corpus(p, lines=80)
    spec = sh.plan_shards([p], 1)[0]
    data = open(p, "rb").read()
    # a cursor in the middle of a line: the prefix boundary must be
    # pushed forward to just past the NEXT newline, never mid-token
    cursor = data.index(b"\n") + 5
    ranges = sh.split_remaining([p], spec, cursor, ways=2, min_bytes=64)
    assert ranges is not None
    b0 = ranges[0][1]
    assert b0 > cursor
    assert data[b0 - 1:b0] == b"\n"
    _assert_partition([p], spec, ranges)


def test_split_remaining_refusals(tmp_path):
    p = str(tmp_path / "d.txt")
    write_corpus(p, lines=40)
    spec = sh.plan_shards([p], 1)[0]
    # cursor at / past the end: nothing left to redistribute
    assert sh.split_remaining([p], spec, spec.size, 2, 64) is None
    assert sh.split_remaining([p], spec, spec.size + 99, 2, 64) is None
    # remainder under the amortization floor falls back to a backup
    assert sh.split_remaining([p], spec, 0, 2,
                              min_bytes=spec.size + 1) is None
    # a giant single line collapses every cut: nothing to split
    g = str(tmp_path / "giant.txt")
    with open(g, "wb") as f:
        f.write(b"x" * 4000 + b"\n")
    gspec = sh.plan_shards([g], 1)[0]
    assert sh.split_remaining([g], gspec, 0, 4, min_bytes=2) is None


def test_subrange_separator_at_range_end_regression(tmp_path):
    # The PR-15 regression re-run on SUB-ranges: a sub-range boundary
    # landing on the inter-file separator byte must keep the slice
    # byte-exact.  Exhaustive over every cursor of a tiny two-file
    # stream: whenever a split applies, the sub-slices reassemble the
    # remainder exactly — separator bytes included.
    p1 = str(tmp_path / "a.txt")
    p2 = str(tmp_path / "b.txt")
    with open(p1, "wb") as f:
        f.write(b"hello\n")
    with open(p2, "wb") as f:
        f.write(b"world\n")
    files = [p1, p2]
    total = sh.stream_total_bytes(files)
    whole = b"".join(sh.read_stream_range(files, 0, total))
    assert whole == b"hello\n\nworld\n"
    spec = sh.ShardSpec(0, 0, total)
    for cursor in range(total):
        ranges = sh.split_remaining(files, spec, cursor, ways=2,
                                    min_bytes=2)
        if ranges is None:
            continue
        _assert_partition(files, spec, ranges)


def test_subrange_wordcount_merge_matches_oracle(tmp_path):
    # Token safety of the sub-shard cuts, end to end: per-sub-range
    # counts merge to the whole-shard oracle.
    p = str(tmp_path / "e.txt")
    data = write_corpus(p, lines=70)
    spec = sh.plan_shards([p], 1)[0]
    ranges = sh.split_remaining([p], spec, 333, ways=3, min_bytes=64)
    assert ranges is not None and len(ranges) >= 3
    parts = [sh.format_wordcount_counts(sh.wordcount_host_oracle(
        sh.read_stream_range([p], s, e))) for s, e in ranges]
    assert sh.merge_wordcount(parts) == \
        sh.format_wordcount_counts(sh.wordcount_host_oracle([data]))


# ── forced re-split state machine (handlers direct, no jax) ──────────


def mk_coord(tmp_path, n_shards=2, journal=True, **cfg_kw):
    p = str(tmp_path / "in.txt")
    write_corpus(p, lines=200)
    plan = sh.plan_shards([p], n_shards)
    kw = dict(workdir=str(tmp_path), spec_floor_s=0.05,
              shard_timeout_s=5.0, spec_setup_s=8.0, spec_resplit=True,
              spec_resplit_ways=2, spec_resplit_min_bytes=64)
    kw.update(cfg_kw)
    if journal:
        kw["journal_path"] = str(tmp_path / "shards.journal")
    cfg = JobConfig(n_reduce=0, **kw)
    c = Coordinator([p], 0, cfg, shard_plan=plan,
                    shard_opts={"knobs": {"engine": "wordcount"}})
    return c, plan


def beat(c, r, confirmed=1, ckpts=0, cursor=0, wid=None):
    return c.shard_progress({"WorkerId": wid or "wX",
                             "Shard": r["Shard"], "Attempt": r["Attempt"],
                             "Sub": r.get("Sub", -1),
                             "Confirmed": confirmed, "Ckpts": ckpts,
                             "Cursor": cursor, "ResumeCursor": 0})


def commit(c, r, crc=1, payload=b"a 1\n", wid=None):
    with open(r["OutPart"], "wb") as f:
        f.write(payload)
    return c.commit_shard({"WorkerId": wid or "wX", "Shard": r["Shard"],
                           "Sub": r.get("Sub", -1),
                           "Attempt": r["Attempt"], "Crc": crc})


def force_resplit(c, plan, cursor=600):
    """Drive the coordinator to a fired re-split: w1 straggles on shard
    0 with ``cursor`` confirmed bytes and a checkpoint, w2 commits
    shard 1 then idles into the re-split trigger.  Returns (straggler
    assignment, first sub assignment)."""
    r0 = c.request_shard({"WorkerId": "w1"})
    r1 = c.request_shard({"WorkerId": "w2"})
    assert {r0["Shard"], r1["Shard"]} == {0, 1}
    if r0["Shard"] != 0:
        r0, r1 = r1, r0
    beat(c, r0, confirmed=3, ckpts=1, cursor=cursor, wid="w1")
    assert commit(c, r1, wid="w2")["Win"]
    time.sleep(0.12)  # past the floor: w1 is silent, w2 idles
    rs = c.request_shard({"WorkerId": "w2"})
    assert rs["TaskStatus"] == int(TaskStatus.SHARD)
    assert rs.get("Sub") is not None, rs
    return r0, rs


def test_resplit_fires_and_dispatches_subs(tmp_path):
    c, plan = mk_coord(tmp_path)
    try:
        r0, rs = force_resplit(c, plan, cursor=600)
        spec = plan[0]
        # sub 0 is the straggler's confirmed prefix: it adopts the
        # parent chain and carries the PARENT's range identity tag
        assert rs["Sub"] == 0
        assert rs["Start"] == spec.start and rs["End"] > rs["Start"]
        assert rs["End"] >= spec.start + 600  # newline-aligned past cursor
        assert (rs["TagStart"], rs["TagEnd"]) == (spec.start, spec.end)
        assert rs["ParentChain"] == r0["Attempt"]
        s = c.spec_stats()
        assert s["resplits"] == 1
        assert s["backup_dispatches"] == 0  # resplit preempted backup
        assert s["subshards"] == 3  # prefix + 2-way remainder
        assert s["subshard_dispatches"] == 1
        # the remaining subs dispatch to other idle workers, in order,
        # partitioning the shard exactly
        ra = c.request_shard({"WorkerId": "w3"})
        rb = c.request_shard({"WorkerId": "w4"})
        assert (ra["Sub"], rb["Sub"]) == (1, 2)
        assert rs["End"] == ra["Start"] and ra["End"] == rb["Start"]
        assert rb["End"] == spec.end
        for r in (ra, rb):
            assert r["ParentChain"] is None
        # the split was journaled BEFORE dispatch
        recs = [json.loads(l) for l in
                open(str(tmp_path / "shards.journal"))]
        assert any(r.get("kind") == "resplit" and r["task"] == 0
                   for r in recs)
    finally:
        c.close()


def test_sub_commits_resolve_split_and_cancel_straggler(tmp_path):
    c, plan = mk_coord(tmp_path)
    try:
        r0, rs = force_resplit(c, plan)
        ra = c.request_shard({"WorkerId": "w3"})
        rb = c.request_shard({"WorkerId": "w4"})
        for i, (r, w) in enumerate(((rs, "w2"), (ra, "w3"))):
            assert commit(c, r, crc=10 + i, wid=w)["Win"]
            # split not yet resolved: the straggler keeps racing
            assert not beat(c, r0, confirmed=4, cursor=700,
                            wid="w1")["Cancel"]
        assert commit(c, rb, crc=12, wid="w4")["Win"]
        # the last sub commit resolved the shard: straggler cancelled,
        # its late full-range commit loses WITHOUT counting a duplicate
        assert beat(c, r0, confirmed=5, cursor=800, wid="w1")["Cancel"]
        assert not commit(c, r0, wid="w1")["Win"]
        assert c.done()
        s = c.spec_stats()
        assert s["split_shards"] == 1 and s["resolved"] == 2
        assert s["subshard_commits"] == 3
        assert s["duplicate_commits"] == 0
        assert s["commit_losses"] == 1
        # final outputs: sub files in k order, then shard 1's full file
        outs = c.final_outputs()
        base = os.path.join(str(tmp_path), "mr-shard-out-0")
        assert outs == [base + ".s0", base + ".s1", base + ".s2",
                        os.path.join(str(tmp_path), "mr-shard-out-1")]
        assert all(os.path.exists(o) for o in outs)
    finally:
        c.close()


def test_full_range_commit_overruns_open_split(tmp_path):
    c, plan = mk_coord(tmp_path)
    try:
        r0, rs = force_resplit(c, plan)
        ra = c.request_shard({"WorkerId": "w3"})
        assert commit(c, rs, wid="w2")["Win"]  # one sub in, split open
        # the straggler outruns its own split: full-range commit wins
        # the WHOLE shard while any sub is still uncommitted
        assert commit(c, r0, crc=77, wid="w1")["Win"]
        assert c.done()
        # the losing sub's committed output was reaped — exactly one
        # committed copy of every byte survives
        assert not os.path.exists(
            os.path.join(str(tmp_path), "mr-shard-out-0.s0"))
        assert c.final_outputs() == [
            os.path.join(str(tmp_path), "mr-shard-out-0"),
            os.path.join(str(tmp_path), "mr-shard-out-1")]
        # a late sub commit loses and counts no duplicate
        assert not commit(c, ra, wid="w3")["Win"]
        s = c.spec_stats()
        assert s["duplicate_commits"] == 0
        assert s["split_shards"] == 0
        assert s["winning_attempts"]["0"] == r0["Attempt"]
    finally:
        c.close()


def test_small_remainder_falls_back_to_backup(tmp_path):
    c, plan = mk_coord(tmp_path, spec_resplit_min_bytes=1 << 30)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        r1 = c.request_shard({"WorkerId": "w2"})
        beat(c, r0, confirmed=3, ckpts=1, cursor=600,
             wid="w1" if r0["Shard"] == 0 else "w2")
        beat(c, r1, confirmed=3, ckpts=1, cursor=600,
             wid="w2" if r0["Shard"] == 0 else "w1")
        time.sleep(0.12)
        rb = c.request_shard({"WorkerId": "w3"})
        # remainder under the split floor: a plain full-range backup
        # covers the shard instead
        assert rb["TaskStatus"] == int(TaskStatus.SHARD)
        assert rb.get("Sub") is None
        s = c.spec_stats()
        assert s["resplits"] == 0 and s["subshards"] == 0
        assert s["backup_dispatches"] == 1
    finally:
        c.close()


def test_journal_replays_split_state(tmp_path):
    c, plan = mk_coord(tmp_path)
    p = c.files[0]
    try:
        r0, rs = force_resplit(c, plan)
        ra = c.request_shard({"WorkerId": "w3"})
        assert commit(c, rs, crc=5, wid="w2")["Win"]
    finally:
        c.close()
    # a fresh coordinator on the same journal: the split replays as
    # live sub-shard state — committed sub preserved, the rest (and
    # NEVER the full range) dispatchable
    cfg = JobConfig(n_reduce=0, workdir=str(tmp_path),
                    journal_path=str(tmp_path / "shards.journal"),
                    spec_resplit=True, spec_resplit_ways=2,
                    spec_resplit_min_bytes=64)
    c2 = Coordinator([p], 0, cfg, shard_plan=plan, shard_opts={})
    try:
        s = c2.spec_stats()
        assert s["subshards"] == 3
        assert s["committed"] == 1  # shard 1's full-range commit
        assert not c2.done()
        picks = [c2.request_shard({"WorkerId": f"w{i}"})
                 for i in range(5, 8)]
        subs = sorted(r["Sub"] for r in picks
                      if r["TaskStatus"] == int(TaskStatus.SHARD))
        assert subs == [1, 2]  # sub 0 replayed committed; no full range
        for r in picks:
            if r["TaskStatus"] == int(TaskStatus.SHARD):
                assert commit(c2, r, wid="wZ")["Win"]
        assert c2.done()
        assert c2.spec_stats()["duplicate_commits"] == 0
    finally:
        c2.close()


# ── pipelined plan executor (jax) ─────────────────────────────────────


jax = pytest.importorskip("jax")

from dsi_tpu.ckpt.fault import FaultInjected, reset_faults  # noqa: E402
from dsi_tpu.parallel.shuffle import default_mesh  # noqa: E402
from dsi_tpu.plan import (grep_cascade_plan, grep_wordcount_plan,  # noqa: E402
                          run_plan, wordcount_topk_plan)

MESH = None


def mesh():
    global MESH
    if MESH is None:
        MESH = default_mesh(8)
    return MESH


def plan_corpus(n=420):
    lines = []
    for i in range(n):
        if i % 3 == 0:
            lines.append(f"the quick w{i % 29} fox likes the pond")
        else:
            lines.append(f"unrelated filler row{i} content")
    return ("\n".join(lines) + "\n").encode()


def gw_plan(tmp_path, **kw):
    p = tmp_path / "corpus.txt"
    if not p.exists():
        p.write_bytes(plan_corpus())
    kw.setdefault("chunk_bytes", 1 << 9)
    return grep_wordcount_plan("the", paths=[str(p)], **kw)


@pytest.mark.parametrize("shards,mesh_shards", [
    (0, None),
    (3, None),
    (3, 8),
])
def test_pipelined_chain_parity_grid(tmp_path, shards, mesh_shards):
    kw = dict(mesh_shards=mesh_shards)
    st_p, st_s = {}, {}
    pipe = run_plan(gw_plan(tmp_path, **kw), mesh=mesh(),
                    pipelined=True, stage_shards=shards, stats=st_p)
    # the oracle twin: strictly sequential staged execution under the
    # SAME shard geometry (sharded grep merges zero the order-sensitive
    # topk sample, so parity holds shard-geometry-to-like)
    staged = run_plan(gw_plan(tmp_path, **kw), mesh=mesh(),
                      staged=True, stage_shards=shards, stats=st_s)
    assert pipe.results["grep"] == staged.results["grep"]
    assert pipe.final == staged.final
    assert len(pipe.final) > 0
    assert st_p["plan_pipelined"] == 1
    assert st_p["plan_stage_shards"] == shards
    assert st_p["plan_intermediate_bytes"] == 0  # still device-resident
    # the overlap the pipelining bought is attributed: sealed buffers
    # were consumed while the producer still ran
    assert st_p["plan_overlap_s"] > 0
    assert st_s["plan_pipelined"] == 0


def test_pipelined_crash_resume_mid_overlap(tmp_path, monkeypatch):
    want = run_plan(gw_plan(tmp_path), mesh=mesh()).final
    ck = str(tmp_path / "ck")
    # the consumer's 2nd advance happens INSIDE the stage_overlap
    # window, while the producer is still mid-stream
    monkeypatch.setenv("DSI_FAULT_POINT", "plan-stage1-advance")
    monkeypatch.setenv("DSI_FAULT_STEP", "2")
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    reset_faults()
    with pytest.raises(FaultInjected):
        run_plan(gw_plan(tmp_path), mesh=mesh(), pipelined=True,
                 checkpoint_dir=ck)
    monkeypatch.delenv("DSI_FAULT_POINT")
    monkeypatch.delenv("DSI_FAULT_STEP")
    monkeypatch.delenv("DSI_FAULT_MODE")
    st: dict = {}
    res = run_plan(gw_plan(tmp_path), mesh=mesh(), pipelined=True,
                   checkpoint_dir=ck, resume=True, stats=st)
    assert res.final == want
    # nothing usable could have committed mid-overlap: the spent-relay
    # rule re-runs the producer rather than feeding an empty relay
    assert st["plan_resumed_stages"] == 0


def test_staged_never_pipelines(tmp_path):
    st: dict = {}
    run_plan(gw_plan(tmp_path), mesh=mesh(), staged=True,
             pipelined=True, stats=st)
    assert st["plan_pipelined"] == 0
    assert st["plan_overlap_s"] == 0


# ── the two new stage kinds ──────────────────────────────────────────


def cascade_corpus(n=300):
    lines = []
    for i in range(n):
        if i % 4 == 0:
            lines.append(f"alpha beta row{i}")   # matches both stages
        elif i % 4 == 1:
            lines.append(f"alpha only row{i}")   # first stage only
        else:
            lines.append(f"nothing here row{i}")
    return ("\n".join(lines) + "\n").encode()


def test_grep_cascade_parity_and_narrowing():
    data = cascade_corpus()
    plan = grep_cascade_plan("alpha", "beta", data=data,
                             chunk_bytes=1 << 9)
    chained = run_plan(plan, mesh=mesh())
    staged = run_plan(grep_cascade_plan("alpha", "beta", data=data,
                                        chunk_bytes=1 << 9),
                      mesh=mesh(), staged=True)
    assert chained.results == staged.results
    g1, g2 = chained.results["grep1"], chained.results["grep2"]
    assert g1.matched == 150   # every alpha line
    assert g2.matched == 75    # narrowed to alpha∩beta
    assert g2.lines == g1.matched  # stage 2 reads ONLY stage-1 matches


def test_wordcount_topk_parity_and_order(tmp_path):
    p = tmp_path / "wc.txt"
    p.write_bytes(plan_corpus())
    for shards in (0, 3):
        plan = wordcount_topk_plan(5, paths=[str(p)],
                                   chunk_bytes=1 << 9)
        chained = run_plan(plan, mesh=mesh(), stage_shards=shards)
        staged = run_plan(wordcount_topk_plan(5, paths=[str(p)],
                                              chunk_bytes=1 << 9),
                          mesh=mesh(), staged=True, stage_shards=shards)
        assert chained.final == staged.final
        assert len(chained.final) == 5
        counts = [c for c, _w in chained.final]
        assert counts == sorted(counts, reverse=True)
        # deterministic tie-break: (-count, word)
        assert list(chained.final) == sorted(
            chained.final, key=lambda r: (-r[0], r[1]))
        # five words tie at the top (280 each — the alphabetic
        # tokenizer folds "row123" to "row"); the word tie-break
        # orders them alphabetically
        assert list(chained.final) == [
            (280, "content"), (280, "filler"), (280, "row"),
            (280, "the"), (280, "unrelated")]
