"""Overlapped shuffle (ISSUE 18): pipelined reduce-side fetches,
streaming decode/merge, and net-served plan relays.

Layers, cheapest first:

* keep-alive transport units — :class:`rpc.StreamConn` multi-fetch
  reuse, poisoning after an error, the per-dialer :class:`ConnPool`
  redial-once on a stale cached connection;
* fetch-failure taxonomy units (satellite) — an unknown wirecodec flag
  and a torn LOCAL spool read both surface as :class:`FetchFailure`
  and both count in ``net_fetch_failures``;
* pipeline units — the parity grid (window 1/4/8 × wordcount/indexer
  reduce → byte-identical ``mr-out-*``), first-failure-wins with
  in-flight peers drained, and the slow-peer overlap attribution
  (``net_overlap_s`` > 0 pipelined, absent serial);
* journal × net units (satellite) — a coordinator killed between map
  commit and reduce dispatch replays the partition location registry
  from the journal, and reduce-output locations survive the same way;
* stage-payload codec units — ``pack_commit``/``unpack_commit``
  round-trip;
* the differential harness — ``mrrun --net --journal`` (accepted and
  parity-gated now), the off-loopback HMAC smoke
  (``DSI_NET_BIND=127.0.0.2`` + ``DSI_MR_SECRET`` — the CI auth-path
  exercise, plus the no-secret refusal), and ``planrun --hosts
  --check``: net-served plan relays, share-nothing audited, parity
  against the in-process chain.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dsi_tpu.config import JobConfig
from dsi_tpu.mr import rpc
from dsi_tpu.mr.coordinator import Coordinator
from dsi_tpu.mr.types import TaskStatus
from dsi_tpu.net import ConnPool, FetchPipeline, PartitionServer
from dsi_tpu.net.fetch import (FetchFailure, fetch_partition,
                               fetch_window_from_env,
                               run_reduce_task_net)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ── keep-alive transport ───────────────────────────────────────────────


def test_stream_conn_multi_fetch_reuse():
    served = []
    srv = rpc.StreamServer(
        "tcp:127.0.0.1:0",
        {"Blob": lambda args: served.append(args["N"]) or
                              b"payload-%d" % args["N"]})
    srv.start()
    try:
        with rpc.StreamConn(srv.address, timeout=10.0) as conn:
            for n in range(3):
                assert conn.fetch("Blob", {"N": n}) == b"payload-%d" % n
            assert conn.fetches == 3
        assert served == [0, 1, 2]
    finally:
        srv.close()


def test_stream_conn_poisoned_after_error():
    srv = rpc.StreamServer("tcp:127.0.0.1:0",
                           {"Blob": lambda args: b"ok"})
    srv.start()
    try:
        conn = rpc.StreamConn(srv.address, timeout=10.0)
        try:
            with pytest.raises(rpc.StreamError, match="no such method"):
                conn.fetch("Nope")
            # the server closed its end on the error response; the conn
            # must refuse reuse rather than read a desynchronized stream
            with pytest.raises(rpc.StreamError, match="already failed"):
                conn.fetch("Blob")
        finally:
            conn.close()
    finally:
        srv.close()


def test_conn_pool_redials_stale_keepalive(tmp_path):
    ps = PartitionServer(str(tmp_path / "spool"))
    ps.start()
    try:
        ps.put("mr-0-0", b"bytes one\n")
        with ConnPool(timeout=10.0) as pool:
            assert fetch_partition(ps.address, "mr-0-0",
                                   pool=pool) == b"bytes one\n"
            # sever the cached connection under the pool (the server's
            # idle timeout in real fleets); the next fetch must redial
            # once and succeed, not surface the stale socket's error
            pool._conns[ps.address]._sock.close()
            assert fetch_partition(ps.address, "mr-0-0",
                                   pool=pool) == b"bytes one\n"
    finally:
        ps.close()


# ── fetch-failure taxonomy (satellite) ─────────────────────────────────


def test_unknown_codec_flag_is_fetch_failure_and_counted():
    # a producer shipping a flag byte this consumer does not know is a
    # curable fetch failure (re-fetch from a replacement), NOT a bare
    # StreamError escaping into the reduce loop
    srv = rpc.StreamServer("tcp:127.0.0.1:0",
                           {"Fetch": lambda args: b"Xcorrupt"})
    srv.start()
    try:
        stats: dict = {}
        with pytest.raises(FetchFailure) as ei:
            fetch_partition(srv.address, "mr-0-0", stats=stats,
                            timeout=10.0)
        assert isinstance(ei.value.cause, rpc.StreamError)
        assert "unknown codec flag" in str(ei.value.cause)
        assert stats["net_fetch_failures"] == 1
    finally:
        srv.close()


def test_local_read_oserror_is_fetch_failure_and_counted(tmp_path):
    # the locality short-circuit's failure mode: our own advertised
    # address but the spool entry is unreadable (here: a directory) —
    # wrapped and counted exactly like a remote failure
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "mr-3-1"))
    stats: dict = {}
    with pytest.raises(FetchFailure) as ei:
        fetch_partition("tcp:127.0.0.1:9", "mr-3-1", stats=stats,
                        own_addr="tcp:127.0.0.1:9", local_root=root)
    assert isinstance(ei.value.cause, OSError)
    assert stats["net_fetch_failures"] == 1


# ── the prefetch pipeline ──────────────────────────────────────────────


def test_fetch_window_from_env(monkeypatch):
    monkeypatch.delenv("DSI_NET_FETCH_WINDOW", raising=False)
    assert fetch_window_from_env() == 4
    monkeypatch.setenv("DSI_NET_FETCH_WINDOW", "8")
    assert fetch_window_from_env() == 8
    monkeypatch.setenv("DSI_NET_FETCH_WINDOW", "0")
    assert fetch_window_from_env() == 1  # clamped: 0 would deadlock
    monkeypatch.setenv("DSI_NET_FETCH_WINDOW", "garbage")
    assert fetch_window_from_env() == 4


def _spool_partitions(tmp_path, n_maps, reduce_task=0):
    """n_maps producers, each serving one KV partition for one reduce
    task; returns ``map_locs`` and the servers."""
    servers, map_locs = [], {}
    for m in range(n_maps):
        srv = PartitionServer(str(tmp_path / f"spool-{m}"))
        srv.start()
        servers.append(srv)
        lines = [json.dumps({"Key": f"w{(m * 7 + i) % 11:02d}",
                             "Value": "1"})
                 for i in range(120)]
        srv.put(f"mr-{m}-{reduce_task}",
                ("\n".join(lines) + "\n").encode())
        map_locs[str(m)] = srv.address
    return map_locs, servers


@pytest.mark.parametrize("app", ["wc", "indexer"])
def test_parity_grid_windows_are_byte_identical(tmp_path, app):
    # the tentpole's determinism claim: mr-out-<r> bytes are identical
    # at ANY window — window 1 being the literal pre-pipeline serial
    # loop, so 4 and 8 are bit-identical to it by transitivity
    from dsi_tpu.mr.plugin import load_plugin

    _mapf, reducef = load_plugin(app)
    map_locs, servers = _spool_partitions(tmp_path, n_maps=6)
    try:
        outs = {}
        for window in (1, 4, 8):
            wd = str(tmp_path / f"out-w{window}")
            os.makedirs(wd)
            stats: dict = {}
            name = run_reduce_task_net(reducef, 0, map_locs,
                                       workdir=wd, stats=stats,
                                       window=window)
            assert stats["net_prefetch_window"] == window
            if window == 1:
                assert "net_overlap_s" not in stats  # serial: none
            with open(os.path.join(wd, name), "rb") as f:
                outs[window] = f.read()
        assert outs[1] == outs[4] == outs[8]
        assert outs[1]  # the grid compared real content
    finally:
        for srv in servers:
            srv.close()


def test_pipeline_first_failure_wins_and_drains(tmp_path):
    map_locs, servers = _spool_partitions(tmp_path, n_maps=5)
    try:
        items = [(m, map_locs[str(m)],
                  f"mr-{m}-0" if m != 2 else "mr-missing-0")
                 for m in range(5)]
        pipe = FetchPipeline(items, window=3)
        got = []
        with pytest.raises(FetchFailure) as ei:
            for task, raw in pipe:
                got.append(task)
        # the failure is attributed to the producer whose bytes were
        # lost, with the original cause chained
        assert ei.value.task == 2
        assert ei.value.name == "mr-missing-0"
        # submission order up to the failure — the consumer stops
        # waiting the moment ANY dialer errors, so how far it got
        # before the (fast) failure landed is a race; the ORDER is not
        assert got == [0, 1][:len(got)]
        # in-flight peers were drained: no dialer thread survives
        assert not any(t.is_alive() for t in pipe._threads)
    finally:
        for srv in servers:
            srv.close()


def test_slow_peer_overlap_attribution(tmp_path):
    # a fake slow peer (injected per-chunk serve latency): the pipeline
    # hides its wire time behind the consumer (net_overlap_s > 0); the
    # serial path cannot, by construction, and reports none
    from dsi_tpu.mr.plugin import load_plugin

    _mapf, reducef = load_plugin("wc")
    map_locs, servers = _spool_partitions(tmp_path, n_maps=4)
    for srv in servers:
        srv._chunk_sleep_s = 0.05
    try:
        serial: dict = {}
        wd1 = str(tmp_path / "serial")
        os.makedirs(wd1)
        run_reduce_task_net(reducef, 0, map_locs, workdir=wd1,
                            stats=serial, window=1)
        piped: dict = {}
        wd4 = str(tmp_path / "piped")
        os.makedirs(wd4)
        run_reduce_task_net(reducef, 0, map_locs, workdir=wd4,
                            stats=piped, window=4)
        assert "net_overlap_s" not in serial
        assert piped["net_overlap_s"] > 0
        assert piped["net_fetch_wait_s"] >= 0
        assert piped["net_prefetch_window"] == 4
        with open(os.path.join(wd1, "mr-out-0"), "rb") as a, \
                open(os.path.join(wd4, "mr-out-0"), "rb") as b:
            assert a.read() == b.read()
    finally:
        for srv in servers:
            srv.close()


# ── adaptive fetch window (ISSUE 19 satellite) ─────────────────────────


def test_fetch_window_max_from_env(monkeypatch):
    from dsi_tpu.net.fetch import fetch_window_max_from_env

    monkeypatch.delenv("DSI_NET_FETCH_WINDOW_MAX", raising=False)
    assert fetch_window_max_from_env(4) == 4      # unset: widening off
    monkeypatch.setenv("DSI_NET_FETCH_WINDOW_MAX", "16")
    assert fetch_window_max_from_env(4) == 16
    monkeypatch.setenv("DSI_NET_FETCH_WINDOW_MAX", "2")
    assert fetch_window_max_from_env(4) == 4      # clamped >= window
    monkeypatch.setenv("DSI_NET_FETCH_WINDOW_MAX", "garbage")
    assert fetch_window_max_from_env(4) == 4      # malformed: off


def test_adaptive_window_widens_on_slow_peers(tmp_path):
    # slow producers (injected per-chunk serve latency) starve the
    # consumer → the wait-dominated pipeline widens toward the ceiling,
    # attributes the final width, and the bytes stay identical to the
    # window-1 serial loop (the parity-grid transitivity claim extends
    # to ANY widening schedule, because decode order is submission
    # order regardless of width)
    from dsi_tpu.mr.plugin import load_plugin

    _mapf, reducef = load_plugin("wc")
    map_locs, servers = _spool_partitions(tmp_path, n_maps=8)
    for srv in servers:
        srv._chunk_sleep_s = 0.05
    try:
        wd1 = str(tmp_path / "serial")
        os.makedirs(wd1)
        serial: dict = {}
        run_reduce_task_net(reducef, 0, map_locs, workdir=wd1,
                            stats=serial, window=1)
        assert serial["net_prefetch_window"] == 1
        wda = str(tmp_path / "adaptive")
        os.makedirs(wda)
        adaptive: dict = {}
        run_reduce_task_net(reducef, 0, map_locs, workdir=wda,
                            stats=adaptive, window=2, max_window=8)
        assert adaptive["net_prefetch_window"] > 2    # it widened
        assert adaptive["net_prefetch_window"] <= 8   # bounded
        with open(os.path.join(wd1, "mr-out-0"), "rb") as a, \
                open(os.path.join(wda, "mr-out-0"), "rb") as b:
            assert a.read() == b.read()
    finally:
        for srv in servers:
            srv.close()


def test_adaptive_window_off_at_ceiling_and_serial(tmp_path):
    # max_window == window → no widening no matter how slow the peers;
    # window 1 ignores any ceiling (serial stays the literal serial
    # loop, the parity grid's anchor)
    map_locs, servers = _spool_partitions(tmp_path, n_maps=4)
    for srv in servers:
        srv._chunk_sleep_s = 0.05
    try:
        items = [(m, map_locs[str(m)], f"mr-{m}-0") for m in range(4)]
        pipe = FetchPipeline(items, window=2, max_window=2)
        list(pipe)
        assert pipe.window_effective == 2
        stats: dict = {}
        pipe1 = FetchPipeline(items, window=1, max_window=8,
                              stats=stats)
        list(pipe1)
        assert pipe1.window_effective == 1
        assert stats["net_prefetch_window"] == 1
    finally:
        for srv in servers:
            srv.close()


# ── journal × net (satellite): replayed location registry ──────────────


def _drive_maps(c, addr_of):
    tasks = []
    while True:
        r = c.request_task({"WorkerId": "w", "Addr": addr_of(0)})
        if r["TaskStatus"] != TaskStatus.MAP:
            break
        tasks.append(r["CMap"])
    for m in tasks:
        c.map_complete({"TaskNumber": m, "Addr": addr_of(m),
                        "PartSizes": [100 * (m + 1)] * c.n_reduce})


def test_journal_replay_restores_map_locations(tmp_path):
    # the exact crash window the satellite names: every map committed
    # (and journaled), coordinator dies BEFORE any reduce dispatch —
    # the successor must re-learn where the partitions live or every
    # reducer starves
    jpath = str(tmp_path / "journal")
    cfg = JobConfig(n_reduce=1, net_shuffle=True, journal_path=jpath,
                    workdir=str(tmp_path))
    c1 = Coordinator(["in-0", "in-1"], 1, cfg)
    _drive_maps(c1, lambda m: f"tcp:10.0.0.{m}:5000")
    c1.close()

    c2 = Coordinator(["in-0", "in-1"], 1, cfg)
    try:
        r = c2.request_task({"WorkerId": "w2", "Addr": "tcp:10.0.0.9:1"})
        assert r["TaskStatus"] == TaskStatus.REDUCE and r["Net"] is True
        assert r["MapLocs"] == {"0": "tcp:10.0.0.0:5000",
                                "1": "tcp:10.0.0.1:5000"}
    finally:
        c2.close()


def test_journal_replay_restores_output_locations(tmp_path):
    jpath = str(tmp_path / "journal")
    cfg = JobConfig(n_reduce=1, net_shuffle=True, journal_path=jpath,
                    workdir=str(tmp_path))
    c1 = Coordinator(["in-0"], 1, cfg)
    _drive_maps(c1, lambda m: "tcp:h:1")
    r = c1.request_task({"WorkerId": "w", "Addr": "tcp:h:1"})
    c1.reduce_complete({"TaskNumber": r["CReduce"], "Addr": "tcp:h:1",
                        "Name": "mr-out-0", "Crc": 42})
    assert c1.done()
    c1.close()

    c2 = Coordinator(["in-0"], 1, cfg)
    try:
        assert c2.done()
        assert c2.output_locations() == {0: ("tcp:h:1", "mr-out-0", 42)}
        # and the replayed registry is only ADVISORY: a fetch failure
        # still resets the producer for re-execution (§3.4 convergence)
        assert c2.refetch_reduce(0) is True
        assert not c2.done()
    finally:
        c2.close()


# ── stage-payload codec (net-served plan relays) ───────────────────────


def test_pack_unpack_commit_round_trip():
    from dsi_tpu.plan.stagehost import pack_commit, unpack_commit

    arrays = {"a": np.arange(12, dtype=np.int64).reshape(3, 4),
              "b": np.array([1.5, -2.25])}
    meta = {"kind": "wordcount", "n": 3, "nested": {"k": [1, 2]}}
    blob = pack_commit(arrays, meta)
    got_arrays, got_meta = unpack_commit(blob)
    assert got_meta == meta
    assert sorted(got_arrays) == ["a", "b"]
    assert np.array_equal(got_arrays["a"], arrays["a"])
    assert np.array_equal(got_arrays["b"], arrays["b"])
    with pytest.raises(ValueError, match="not a stage payload"):
        unpack_commit(b"JUNK" + blob[4:])


# ── differential harness ───────────────────────────────────────────────


def _write_corpus(path, lines=1200, seed=7):
    import random

    rnd = random.Random(seed)
    vocab = ["".join(rnd.choice("abcdefgh") for _ in range(4))
             for _ in range(50)]
    with open(path, "w") as f:
        for _ in range(lines):
            f.write(" ".join(rnd.choice(vocab) for _ in range(8)) + "\n")


def _env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update(extra or {})
    return env


def test_mrrun_net_journal_parity(tmp_path):
    # the satellite's headline: --net + --journal is a supported combo
    # now (the location registry is journaled), parity-gated end to end
    corpora = []
    for i in range(2):
        path = str(tmp_path / f"corpus-{i}.txt")
        _write_corpus(path, lines=800, seed=i)
        corpora.append(path)
    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    stats_json = str(tmp_path / "stats.json")
    jpath = str(tmp_path / "journal")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.mrrun",
           "--workers", "2", "--nreduce", "3", "--workdir", wd,
           "--net", "--journal", jpath,
           "--check", "--stats-json", stats_json, "wc"] + corpora
    r = subprocess.run(cmd, env=_env(), cwd=REPO, capture_output=True,
                       text=True, timeout=240)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    # the journal really carries the net location registry
    from dsi_tpu.mr.journal import Journal

    j = Journal(jpath, corpora, 3)
    done_maps, done_reduces = j.replay()
    assert sorted(done_maps) == [0, 1]
    assert sorted(done_reduces) == [0, 1, 2]
    assert set(j.map_locations) == {0, 1}
    assert all(a.startswith("tcp:") for a in j.map_locations.values())
    assert set(j.out_locations) == {0, 1, 2}


def test_partition_server_off_loopback_refused_without_secret(
        tmp_path, monkeypatch):
    monkeypatch.delenv("DSI_MR_SECRET", raising=False)
    with pytest.raises(ValueError, match="refusing to bind"):
        PartitionServer(str(tmp_path / "spool"),
                        bind="tcp:127.0.0.2:0")


def test_mrrun_net_off_loopback_with_hmac(tmp_path):
    # the CI auth-path exercise: a non-loopback bind (127.0.0.2 is off
    # the loopback allowlist but still locally routable) forces the
    # HMAC challenge on EVERY partition fetch — so the auth path runs
    # in tier-1, not only on multi-host fleets
    corpora = []
    for i in range(2):
        path = str(tmp_path / f"corpus-{i}.txt")
        _write_corpus(path, lines=800, seed=i)
        corpora.append(path)
    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    stats_json = str(tmp_path / "stats.json")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.mrrun",
           "--workers", "2", "--nreduce", "3", "--workdir", wd,
           "--net", "--check", "--stats-json", stats_json,
           "wc"] + corpora
    r = subprocess.run(
        cmd, cwd=REPO, capture_output=True, text=True, timeout=240,
        env=_env({"DSI_NET_BIND": "tcp:127.0.0.2:0",
                  "DSI_MR_SECRET": "tier1-ci-secret"}))
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    # off-loopback: the advertised addresses are not the local-read
    # short-circuit's own_addr for OTHER workers, so fetches crossed
    # the (authenticated) wire
    assert s["net_fetches"] > 0
    assert s["net_fetch_failures"] == 0


def test_planrun_hosts_parity_and_share_nothing_audit(tmp_path):
    corpus = str(tmp_path / "corpus.txt")
    _write_corpus(corpus, lines=2000)
    wd = str(tmp_path / "wd")
    stats_json = str(tmp_path / "stats.json")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.planrun",
           "--chain", "wc-topk", "--topk", "8", "--workdir", wd,
           "--hosts", "--check", "--stats-json", stats_json, corpus]
    r = subprocess.run(cmd, env=_env(), cwd=REPO, capture_output=True,
                       text=True, timeout=420)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK (hosts vs chained)" in r.stderr
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    assert s["plan_handoff"] == "net"
    # the inter-stage intermediate really crossed TCP, attributed
    assert s["plan_intermediate_bytes"] > 0
    assert s["net_fetches"] > 0
    # share-nothing: stage dirs cleaned up, no payload in the shared
    # workdir — only the report artifact remains
    left = sorted(os.listdir(wd))
    assert not [n for n in left if n.startswith("stage-")]
    assert not [n for n in left
                if n.startswith("plan-") and n[5:6].isdigit()]
    assert "plan-topk.json" in left
