"""Differential test of the --backend=tpu execution path.

Same discipline as test-mr.sh (oracle vs distributed, merged-sorted-compare,
test-mr.sh:52-53), but the worker executes map tasks through TpuTaskRunner +
the tpu_wc device kernel.  Runs on the CPU platform (conftest.py) — the
kernel is platform-agnostic JAX, so this validates the whole route without
hardware.
"""

import os
import threading
import time

import pytest

pytest.importorskip("jax")

from dsi_tpu.backends.tpu import TpuTaskRunner
from dsi_tpu.config import JobConfig
from dsi_tpu.mr.coordinator import make_coordinator
from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.worker import worker_loop
from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output


@pytest.mark.slow
def test_tpu_backend_distributed_parity(tmp_path):
    wd = str(tmp_path)
    files = ensure_corpus(os.path.join(wd, "inputs"), n_files=4,
                          file_size=60_000)
    want = oracle_output("wc", files, wd)

    cfg = JobConfig(n_reduce=10, workdir=wd,
                    socket_path=os.path.join(wd, "mr.sock"),
                    wait_sleep_s=0.05)
    mapf, reducef = load_plugin("tpu_wc")
    runner = TpuTaskRunner.for_app("tpu_wc")
    assert runner.tpu_map is not None
    c = make_coordinator(files, 10, cfg)
    try:
        workers = [
            threading.Thread(target=worker_loop,
                             args=(mapf, reducef, cfg),
                             kwargs={"task_runner": runner}, daemon=True)
            for _ in range(2)
        ]
        for w in workers:
            w.start()
        deadline = time.time() + 120
        while not c.done():
            assert time.time() < deadline, "tpu-backend job hung"
            time.sleep(0.05)
        for w in workers:
            w.join(timeout=10)
    finally:
        c.close()

    assert merged_output(wd) == want


def test_tpu_wc_app_host_semantics_match_wc():
    """tpu_wc's combiner Map + summing Reduce == wc's Map + counting Reduce."""
    from dsi_tpu.apps import tpu_wc, wc

    text = "the cat and the hat and The end\nthe cat"
    h = {}
    for kv in wc.Map("f", text):
        h.setdefault(kv.key, []).append(kv.value)
    want = {k: wc.Reduce(k, v) for k, v in h.items()}

    t = {}
    for kv in tpu_wc.Map("f", text):
        t.setdefault(kv.key, []).append(kv.value)
    got = {k: tpu_wc.Reduce(k, v) for k, v in t.items()}
    assert got == want


def test_tpu_map_fallback_on_non_ascii():
    from dsi_tpu.apps import tpu_wc

    assert tpu_wc.tpu_map("f", "héllo".encode("utf-8")) is None
    kva = tpu_wc.tpu_map("f", b"plain ascii text plain")
    assert kva is not None
    assert {kv.key: kv.value for kv in kva}["plain"] == "2"


def test_tpu_indexer_matches_host_indexer():
    from dsi_tpu.apps import indexer, tpu_indexer

    raw = b"apple banana apple Cherry banana apple"
    host = indexer.Map("doc1", raw.decode())
    dev = tpu_indexer.tpu_map("doc1", raw)
    assert dev is not None
    assert sorted((kv.key, kv.value) for kv in dev) == \
        sorted((kv.key, kv.value) for kv in host)
    assert tpu_indexer.tpu_map("d", "naïve".encode("utf-8")) is None
    # string-valued reduce unchanged
    assert tpu_indexer.Reduce("w", ["b", "a", "b"]) == "2 a,b"


# ── hash-grouper warm ladder (*_hg AOT entries) ────────────────────────


def test_grouper_parity_hash_vs_sort(monkeypatch):
    """DSI_WC_GROUPER=hash and =sort must produce identical results —
    the env selection the warm ladder now supports on every platform
    changes throughput only, never output."""
    from dsi_tpu.ops.wordcount import count_words_host_result

    raw = (b"the cat and the hat and The end the cat "
           b"some more words with Mixed Case tokens 123 split9here ") * 40
    monkeypatch.setenv("DSI_WC_GROUPER", "sort")
    want = count_words_host_result(raw)
    monkeypatch.setenv("DSI_WC_GROUPER", "hash")
    got = count_words_host_result(raw)
    assert want is not None and got == want


def test_grouper_suffix_convention():
    from dsi_tpu.ops.wordcount import grouper_suffix, warm_groupers

    assert grouper_suffix("sort") == ""  # historical names stay valid
    assert grouper_suffix("hash") == "_hg"
    assert set(warm_groupers()) == {"sort", "hash"}


def test_hash_grouper_warm_ladder_persists_hg_entries(tmp_path):
    """The warm ladder must persist BOTH grouper variants (`*_hg`
    alongside the bare sort names) and the persisted probes must see
    them under an env-pinned hash run — the promotion VERDICT r5 weak #3
    asks for.  Single-device subprocess: persistence is disabled on the
    8-device test mesh by design."""
    import os
    import subprocess
    import sys

    child = (
        "import os\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from dsi_tpu.parallel.streaming import (\n"
        "    kernel_row_persisted, stream_programs_persisted,\n"
        "    warm_kernel_row, warm_stream_aot)\n"
        "from dsi_tpu.backends.aotcache import cache_dir\n"
        "kw = dict(chunk_bytes=1 << 14, u_cap=1 << 10)\n"
        "warm_stream_aot(chunk_bytes=1 << 14, caps=(1 << 10,))\n"
        "warm_kernel_row(**kw)\n"
        "names = os.listdir(cache_dir())\n"
        "assert any('_hg' in n and n.startswith('stream_step_') "
        "for n in names), names\n"
        "assert kernel_row_persisted(**kw)\n"
        "# An env-pinned hash run walks the ('hash','sort') ladder — the\n"
        "# stricter probe must pass from the same warm pass.\n"
        "os.environ['DSI_WC_GROUPER'] = 'hash'\n"
        "assert stream_programs_persisted(**kw)\n"
        "print('hg-ok')\n"
    )
    env = dict(os.environ)
    env["DSI_AOT_CACHE_DIR"] = str(tmp_path / "aot")
    env["DSI_AOT_QUIET"] = "1"
    env.pop("XLA_FLAGS", None)  # single-device process, like the chip
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert p.stdout.strip().splitlines()[-1] == "hg-ok"


# ── block-level Unicode fallback (round 5, VERDICT r4 weakness #5) ─────


def _host_counts(raw: bytes):
    from collections import Counter

    from dsi_tpu.apps.wc import tokenize

    return Counter(tokenize(raw.decode("utf-8", errors="replace")))


def test_unicode_block_fallback_exact():
    from dsi_tpu.apps.tpu_wc import tpu_map

    raw = ("the café serves naïve piñatas and ASCII words\n"
           "café again, plus grüße123mixed and x°y\n"
           + "plain ascii filler line with many common words\n" * 20
           ).encode() + b"bad\xffbytes ok\n"
    kva = tpu_map("f", raw)
    assert kva is not None, "block fallback should keep the device engaged"
    got = {kv.key: int(kv.value) for kv in kva}
    assert got == dict(_host_counts(raw))


def test_unicode_block_fallback_boundaries():
    """High bytes at split edges, runs touching digits, and multi-byte
    sequences must stay token-closed."""
    from dsi_tpu.apps.tpu_wc import tpu_map

    pad = b" filler words to keep the split mostly ascii " * 4
    for raw in (("éstart middle endé".encode() + pad),
                (b"a1\xc3\xa92b c" + pad),
                ("é".encode() * 3 + pad),
                (b"xa " * 2000 + "café".encode() + b" yb" * 2000)):
        kva = tpu_map("f", raw)
        assert kva is not None
        got = {kv.key: int(kv.value) for kv in kva}
        assert got == dict(_host_counts(raw)), raw[:40]


def test_unicode_mostly_nonascii_routes_whole_split_to_host():
    from dsi_tpu.apps.tpu_wc import split_unicode_runs, tpu_map

    raw = "éèê ".encode() * 500
    assert split_unicode_runs(raw) is None
    # tpu_map then defers to the worker's host fallback (returns None).
    assert tpu_map("f", raw) is None


def test_unicode_single_byte_costs_under_ten_percent():
    """The VERDICT r4 target: a split with ONE non-ASCII byte loses
    < 10% of device throughput.  The functional half (both splits
    produce device results) always asserts; the WALL-CLOCK half is
    opt-in via ``DSI_TIMING_ASSERTS=1`` — timing contention on a busy
    1-core tier-1 box flaked the default gate (ADVICE r5 item 3), and a
    load-dependent ratio must not fail a correctness suite.  The typical
    measured ratio (warm kernels, quiet box) is recorded in
    BASELINE.md."""
    import os
    import time

    from dsi_tpu.apps.tpu_wc import tpu_map
    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus("/tmp/uni-corpus", n_files=1, file_size=1 << 20)
    ascii_raw = open(files[0], "rb").read()
    mixed = ascii_raw[:500_000] + "é".encode() + ascii_raw[500_000:]

    def best(raw, reps=3):
        out = []
        for _ in range(reps):
            t0 = time.perf_counter()
            assert tpu_map("f", raw) is not None
            out.append(time.perf_counter() - t0)
        return min(out)

    best(ascii_raw, reps=1)  # warm compile/load
    t_ascii = best(ascii_raw)
    t_mixed = best(mixed)
    ratio = t_mixed / t_ascii
    print(f"unicode single-byte overhead ratio: {ratio:.3f}")
    if os.environ.get("DSI_TIMING_ASSERTS") == "1":
        assert ratio < 1.35, ratio
