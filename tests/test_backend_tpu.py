"""Differential test of the --backend=tpu execution path.

Same discipline as test-mr.sh (oracle vs distributed, merged-sorted-compare,
test-mr.sh:52-53), but the worker executes map tasks through TpuTaskRunner +
the tpu_wc device kernel.  Runs on the CPU platform (conftest.py) — the
kernel is platform-agnostic JAX, so this validates the whole route without
hardware.
"""

import os
import threading
import time

import pytest

pytest.importorskip("jax")

from dsi_tpu.backends.tpu import TpuTaskRunner
from dsi_tpu.config import JobConfig
from dsi_tpu.mr.coordinator import make_coordinator
from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.worker import worker_loop
from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output


@pytest.mark.slow
def test_tpu_backend_distributed_parity(tmp_path):
    wd = str(tmp_path)
    files = ensure_corpus(os.path.join(wd, "inputs"), n_files=4,
                          file_size=60_000)
    want = oracle_output("wc", files, wd)

    cfg = JobConfig(n_reduce=10, workdir=wd,
                    socket_path=os.path.join(wd, "mr.sock"),
                    wait_sleep_s=0.05)
    mapf, reducef = load_plugin("tpu_wc")
    runner = TpuTaskRunner.for_app("tpu_wc")
    assert runner.tpu_map is not None
    c = make_coordinator(files, 10, cfg)
    try:
        workers = [
            threading.Thread(target=worker_loop,
                             args=(mapf, reducef, cfg),
                             kwargs={"task_runner": runner}, daemon=True)
            for _ in range(2)
        ]
        for w in workers:
            w.start()
        deadline = time.time() + 120
        while not c.done():
            assert time.time() < deadline, "tpu-backend job hung"
            time.sleep(0.05)
        for w in workers:
            w.join(timeout=10)
    finally:
        c.close()

    assert merged_output(wd) == want


def test_tpu_wc_app_host_semantics_match_wc():
    """tpu_wc's combiner Map + summing Reduce == wc's Map + counting Reduce."""
    from dsi_tpu.apps import tpu_wc, wc

    text = "the cat and the hat and The end\nthe cat"
    h = {}
    for kv in wc.Map("f", text):
        h.setdefault(kv.key, []).append(kv.value)
    want = {k: wc.Reduce(k, v) for k, v in h.items()}

    t = {}
    for kv in tpu_wc.Map("f", text):
        t.setdefault(kv.key, []).append(kv.value)
    got = {k: tpu_wc.Reduce(k, v) for k, v in t.items()}
    assert got == want


def test_tpu_map_fallback_on_non_ascii():
    from dsi_tpu.apps import tpu_wc

    assert tpu_wc.tpu_map("f", "héllo".encode("utf-8")) is None
    kva = tpu_wc.tpu_map("f", b"plain ascii text plain")
    assert kva is not None
    assert {kv.key: kv.value for kv in kva}["plain"] == "2"


def test_tpu_indexer_matches_host_indexer():
    from dsi_tpu.apps import indexer, tpu_indexer

    raw = b"apple banana apple Cherry banana apple"
    host = indexer.Map("doc1", raw.decode())
    dev = tpu_indexer.tpu_map("doc1", raw)
    assert dev is not None
    assert sorted((kv.key, kv.value) for kv in dev) == \
        sorted((kv.key, kv.value) for kv in host)
    assert tpu_indexer.tpu_map("d", "naïve".encode("utf-8")) is None
    # string-valued reduce unchanged
    assert tpu_indexer.Reduce("w", ["b", "a", "b"]) == "2 a,b"
