"""Network data plane (ISSUE 17): worker-served shuffle over TCP.

Layers, cheapest first:

* stream-transport units — chunked fetch round-trip with CRC trailers,
  the eager-hello version gate (``ProtocolMismatch``, distinct from
  connection-refused), auth, the open-bind refusal;
* KV line-codec units — ``pack_kv``/``unpack_kv`` round-trip on every
  edge shape, and real compression on shuffle-shaped payloads;
* partition-server units — spool hygiene at boot (``reap_spool``),
  basename-only fetch surface, put/fetch round-trip through the codec
  flag, local-read short-circuit, attribution;
* coordinator units — the §3.1 location registry forwarded to
  reducers, locality-aware placement (biggest byte share wins,
  ``locality_hits``), §3.4 map re-execution on ``FetchFailed``, and the
  driver-side ``refetch_reduce``/``refetch_shard`` surface;
* the differential harness — real ``mrrun --net`` / ``shardrun
  --hosts`` fleets with per-process PRIVATE workdirs over localhost
  TCP, byte-identical to the sequential oracle; and the fetch-failure
  chaos arm: a real ``os._exit`` while SERVING (mid-serve) — the
  producer is re-executed, every shard still commits exactly once
  (zero duplicate commits), and parity holds.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from dsi_tpu.config import JobConfig
from dsi_tpu.mr import rpc
from dsi_tpu.mr.coordinator import Coordinator
from dsi_tpu.mr.types import TaskStatus
from dsi_tpu.net import PartitionServer
from dsi_tpu.net.fetch import FetchFailure, fetch_partition
from dsi_tpu.net.partsrv import CODEC_KV, CODEC_RAW, reap_spool
from dsi_tpu.ops.wirecodec import pack_kv, unpack_kv

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def kv_corpus(rows=200) -> bytes:
    # shuffle-shaped: few distinct lines, many repeats — the case the
    # line-dictionary codec exists for
    lines = [b'{"Key":"alpha","Value":"1"}', b'{"Key":"beta","Value":"1"}',
             b'{"Key":"gamma","Value":"1"}']
    return b"\n".join(lines[i % 3] for i in range(rows)) + b"\n"


# ── stream transport ───────────────────────────────────────────────────


def test_stream_fetch_roundtrip_multichunk():
    payload = os.urandom(300_000)  # > default chunk, incompressible
    srv = rpc.StreamServer("tcp:127.0.0.1:0",
                           {"Blob": lambda args: payload},
                           chunk_size=4096)
    srv.start()
    try:
        got = rpc.stream_fetch(srv.address, "Blob", timeout=10.0)
        assert got == payload
    finally:
        srv.close()


def test_stream_fetch_server_side_error_is_stream_error():
    def boom(args):
        raise FileNotFoundError("no such partition")

    srv = rpc.StreamServer("tcp:127.0.0.1:0", {"Fetch": boom})
    srv.start()
    try:
        with pytest.raises(rpc.StreamError, match="no such partition"):
            rpc.stream_fetch(srv.address, "Fetch", timeout=10.0)
        with pytest.raises(rpc.StreamError, match="no such method"):
            rpc.stream_fetch(srv.address, "Nope", timeout=10.0)
    finally:
        srv.close()


def test_connection_refused_is_not_protocol_mismatch():
    # distinct failure taxonomy (satellite): a dead server reads as
    # CoordinatorGone (re-fetch from a replacement), NEVER as the fatal
    # mixed-version diagnosis
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here now
    with pytest.raises(rpc.CoordinatorGone) as ei:
        rpc.stream_fetch(f"tcp:127.0.0.1:{port}", "Fetch", timeout=2.0)
    assert not isinstance(ei.value, rpc.ProtocolMismatch)


def _one_shot_hello_server(hello: bytes):
    """A fake peer that sends ``hello`` and closes — the mixed-version
    / not-a-stream-server cases."""
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(1)

    def serve():
        conn, _ = ls.accept()
        conn.sendall(hello)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return ls, ls.getsockname()[1]


def test_version_mismatch_is_loud():
    wrong = bytes((rpc.PROTOCOL_VERSION + 1,))
    ls, port = _one_shot_hello_server(b"DSN" + wrong)
    try:
        with pytest.raises(rpc.ProtocolMismatch, match="upgrade in "
                                                       "lockstep"):
            rpc.stream_fetch(f"tcp:127.0.0.1:{port}", "Fetch",
                             timeout=5.0)
    finally:
        ls.close()


def test_non_stream_peer_is_protocol_mismatch():
    ls, port = _one_shot_hello_server(b"HTTP")
    try:
        with pytest.raises(rpc.ProtocolMismatch):
            rpc.stream_fetch(f"tcp:127.0.0.1:{port}", "Fetch",
                             timeout=5.0)
    finally:
        ls.close()


def test_stream_auth_round_trip_and_rejection():
    srv = rpc.StreamServer("tcp:127.0.0.1:0",
                           {"Blob": lambda args: b"payload"},
                           secret="hunter2")
    srv.start()
    try:
        assert rpc.stream_fetch(srv.address, "Blob", secret="hunter2",
                                timeout=10.0) == b"payload"
        with pytest.raises(rpc.AuthError):
            rpc.stream_fetch(srv.address, "Blob", secret="wrong",
                             timeout=10.0)
    finally:
        srv.close()


def test_open_bind_without_secret_refused():
    with pytest.raises(ValueError, match="refusing to bind"):
        rpc.StreamServer("tcp:0.0.0.0:0", {"Blob": lambda a: b""})


# ── KV line codec ──────────────────────────────────────────────────────


@pytest.mark.parametrize("raw", [
    b"",
    b"\n",
    b"one line no newline",
    b"one line\n",
    b"a\nb\na\nb\na\n",
    b"trailing\nblank\n\n\nlines\n",
    kv_corpus(64),
    "unicodé line\n".encode(),
])
def test_pack_kv_round_trips(raw):
    assert unpack_kv(pack_kv(raw)) == raw


def test_pack_kv_compresses_shuffle_shape():
    raw = kv_corpus(rows=2000)
    packed = pack_kv(raw)
    assert len(packed) < len(raw) / 2  # 3 distinct lines, 2000 rows
    assert unpack_kv(packed) == raw


# ── partition server ───────────────────────────────────────────────────


def test_reap_spool_boot_hygiene(tmp_path):
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    with open(os.path.join(spool, ".tmp-orphan"), "wb") as f:
        f.write(b"torn write")
    old = os.path.join(spool, "mr-0-0")
    with open(old, "wb") as f:
        f.write(b"dead task's bytes")
    past = time.time() - 7200
    os.utime(old, (past, past))
    with open(os.path.join(spool, "mr-1-0"), "wb") as f:
        f.write(b"live bytes")
    reaped, aged = reap_spool(spool, retention_s=3600.0)
    assert (reaped, aged) == (1, 1)
    assert sorted(os.listdir(spool)) == ["mr-1-0"]


def test_path_of_rejects_escapes(tmp_path):
    ps = PartitionServer(str(tmp_path / "spool"))
    for bad in ("", "../etc/passwd", "a/b", ".tmp-x", ".hidden"):
        with pytest.raises(ValueError):
            ps.path_of(bad)


def test_put_fetch_round_trip_with_attribution(tmp_path):
    ps = PartitionServer(str(tmp_path / "spool"))
    ps.start()
    try:
        raw = kv_corpus(rows=500)
        ps.put("mr-0-1", raw)
        stats: dict = {}
        got = fetch_partition(ps.address, "mr-0-1", stats=stats,
                              timeout=10.0)
        assert got == raw
        assert stats["net_fetches"] == 1
        assert stats["net_bytes_raw"] == len(raw)
        # shuffle-shaped payload really crossed the wire packed
        assert stats["net_bytes_wire"] < len(raw)
        assert stats["net_ratio"] > 1.5
    finally:
        ps.close()


def test_incompressible_payload_ships_raw_flag(tmp_path):
    ps = PartitionServer(str(tmp_path / "spool"))
    ps.start()
    try:
        raw = os.urandom(4096)
        ps.put("blob", raw)
        assert fetch_partition(ps.address, "blob", timeout=10.0) == raw
        # server-side codec decision: packed only when smaller
        assert ps._fetch({"Name": "blob"})[:1] == CODEC_RAW
        ps.put("kv", kv_corpus())
        assert ps._fetch({"Name": "kv"})[:1] == CODEC_KV
    finally:
        ps.close()


def test_local_read_short_circuit(tmp_path):
    spool = str(tmp_path / "spool")
    ps = PartitionServer(spool)  # never started: a socket would fail
    raw = b"my own bytes\n"
    ps.put("mr-2-3", raw)
    stats: dict = {}
    got = fetch_partition(ps.address, "mr-2-3", stats=stats,
                          own_addr=ps.address, local_root=spool)
    assert got == raw
    assert stats == {"net_local_reads": 1}


def test_missing_partition_is_fetch_failure(tmp_path):
    ps = PartitionServer(str(tmp_path / "spool"))
    ps.start()
    try:
        stats: dict = {}
        with pytest.raises(FetchFailure):
            fetch_partition(ps.address, "mr-9-9", stats=stats,
                            timeout=5.0)
        assert stats["net_fetch_failures"] == 1
    finally:
        ps.close()


# ── coordinator: locations, locality, re-execution ─────────────────────


def mk_net(files=2, n_reduce=2):
    return Coordinator([f"in-{i}" for i in range(files)], n_reduce,
                       JobConfig(n_reduce=n_reduce, net_shuffle=True))


def run_maps(c, addr_of):
    """Drive every map to completion WITHOUT consuming a reduce
    assignment; ``addr_of(m)`` is the serving address for map m."""
    tasks = []
    while True:
        r = c.request_task({"WorkerId": "w", "Addr": addr_of(0)})
        if r["TaskStatus"] != TaskStatus.MAP:
            break  # WAITING: every map assigned, none complete yet
        tasks.append(r["CMap"])
    for m in tasks:
        c.map_complete({"TaskNumber": m, "Addr": addr_of(m),
                        "PartSizes": [100] * c.n_reduce})


def test_map_locations_forwarded_to_reducers():
    # §3.1: "the master ... forwards these locations to the reduce
    # workers" — the reduce assignment carries the full registry
    c = mk_net(files=2, n_reduce=1)
    run_maps(c, lambda m: f"tcp:10.0.0.{m}:5000")
    r = c.request_task({"WorkerId": "w", "Addr": "tcp:10.0.0.9:5000"})
    assert r["TaskStatus"] == TaskStatus.REDUCE and r["Net"] is True
    assert r["MapLocs"] == {"0": "tcp:10.0.0.0:5000",
                            "1": "tcp:10.0.0.1:5000"}


def test_locality_placement_prefers_biggest_share():
    c = mk_net(files=2, n_reduce=2)
    a, b = "tcp:hostA:1", "tcp:hostB:1"
    r0 = c.request_task({"WorkerId": "wa", "Addr": a})
    r1 = c.request_task({"WorkerId": "wb", "Addr": b})
    assert {r0["CMap"], r1["CMap"]} == {0, 1}
    # map0 (on A) holds almost all of reduce 1; map1 (on B) almost all
    # of reduce 0 — each host should be handed ITS big partition
    sizes = {r0["CMap"]: [10, 9000], r1["CMap"]: [9000, 10]}
    c.map_complete({"TaskNumber": r0["CMap"], "Addr": a,
                    "PartSizes": sizes[r0["CMap"]]})
    c.map_complete({"TaskNumber": r1["CMap"], "Addr": b,
                    "PartSizes": sizes[r1["CMap"]]})
    got_b = c.request_task({"WorkerId": "wb", "Addr": b})
    got_a = c.request_task({"WorkerId": "wa", "Addr": a})
    assert got_b["TaskStatus"] == got_a["TaskStatus"] == TaskStatus.REDUCE
    assert got_b["CReduce"] == 0 and got_a["CReduce"] == 1
    assert c.net_stats()["locality_hits"] == 2


def test_fetch_failed_reexecutes_map():
    # §3.4: "map tasks executed by the failed worker are re-executed
    # ... since their output is stored on the local disk"
    c = mk_net(files=2, n_reduce=1)
    run_maps(c, lambda m: f"tcp:10.0.0.{m}:5000")
    r = c.request_task({"WorkerId": "wr", "Addr": "tcp:10.0.0.7:1"})
    assert r["TaskStatus"] == TaskStatus.REDUCE
    out = c.fetch_failed({"Map": 0, "Reduce": r["CReduce"],
                          "WorkerId": "wr", "Addr": "tcp:10.0.0.0:5000"})
    assert out["Requeued"] is True
    # barrier re-engaged: the next request is map 0 again, not WAITING
    nxt = c.request_task({"WorkerId": "wx", "Addr": "tcp:10.0.0.8:1"})
    assert nxt["TaskStatus"] == TaskStatus.MAP and nxt["CMap"] == 0
    c.map_complete({"TaskNumber": 0, "Addr": "tcp:10.0.0.8:1",
                    "PartSizes": [100]})
    again = c.request_task({"WorkerId": "wr", "Addr": "tcp:10.0.0.7:1"})
    assert again["TaskStatus"] == TaskStatus.REDUCE
    # the replacement's address replaced the dead one in the registry
    assert again["MapLocs"]["0"] == "tcp:10.0.0.8:1"
    s = c.net_stats()
    assert s["net_refetches"] == 1 and s["net_fetch_failures"] == 1


def test_refetch_reduce_forgets_completion():
    c = mk_net(files=1, n_reduce=1)
    run_maps(c, lambda m: "tcp:h:1")
    r = c.request_task({"WorkerId": "w", "Addr": "tcp:h:1"})
    c.reduce_complete({"TaskNumber": r["CReduce"], "Addr": "tcp:h:1",
                       "Name": "mr-out-0", "Crc": 7})
    assert c.done()
    assert c.output_locations() == {0: ("tcp:h:1", "mr-out-0", 7)}
    assert c.refetch_reduce(0) is True
    assert not c.done() and c.output_locations() == {}
    assert c.refetch_reduce(0) is False  # no longer completed


# ── the differential harness (real fleets, private workdirs) ───────────


def write_corpus(path, lines=3000, seed=7):
    import random

    rnd = random.Random(seed)
    vocab = ["".join(rnd.choice("abcdefgh") for _ in range(4))
             for _ in range(50)]
    with open(path, "w") as f:
        for _ in range(lines):
            f.write(" ".join(rnd.choice(vocab) for _ in range(8)) + "\n")


def _env(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def test_mrrun_net_parity(tmp_path):
    # several input files: multiple producers spread across the two
    # workers, so some shuffle really crosses the wire (one file would
    # let locality placement turn EVERY fetch into a local read)
    corpora = []
    for i in range(3):
        path = str(tmp_path / f"corpus-{i}.txt")
        write_corpus(path, lines=1500, seed=i)
        corpora.append(path)
    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    stats_json = str(tmp_path / "stats.json")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.mrrun",
           "--workers", "2", "--nreduce", "4", "--workdir", wd,
           "--net", "--check", "--stats-json", stats_json,
           "wc"] + corpora
    r = subprocess.run(cmd, env=_env(tmp_path), cwd=REPO,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    assert s["net_fetches"] + s["net_local_reads"] > 0
    assert s["net_bytes_raw"] > s["net_bytes_wire"] > 0
    assert s["net_ratio"] > 1.5  # shuffle crossed the wire packed
    assert s["net_fetch_failures"] == 0 and s["net_refetches"] == 0
    # share-nothing: private spools were cleaned up, only outputs stay
    left = sorted(os.listdir(wd))
    assert not [n for n in left if n.startswith("worker-")]
    assert not [n for n in left
                if n.startswith("mr-")
                and not n.startswith(("mr-out-", "mr-correct"))]


def test_shardrun_hosts_parity(tmp_path):
    corpus = str(tmp_path / "corpus.txt")
    write_corpus(corpus)
    wd = str(tmp_path / "wd")
    stats_json = str(tmp_path / "stats.json")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.shardrun",
           "--engine", "wordcount", "--workers", "2", "--shards", "4",
           "--workdir", wd, "--hosts", "--progress-s", "0.1",
           "--shard-timeout", "5",
           "--check", "--stats-json", stats_json, corpus]
    r = subprocess.run(cmd, env=_env(tmp_path), cwd=REPO,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    assert s["commits"] == s["shards"] == 4
    assert s["duplicate_commits"] == 0
    assert s["net_fetches"] == 4  # the driver pulled every shard output
    assert s["net_fetch_failures"] == 0
    # share-nothing: no worker artifact in the shared dir, spools reaped
    left = sorted(os.listdir(wd))
    assert not [n for n in left if n.startswith("worker-")]
    assert not [n for n in left if n.endswith(".part") or n == ".shards"]


def test_fetch_failure_chaos_reexecutes_producer(tmp_path):
    """The satellite chaos arm: worker 0 takes a REAL ``os._exit``
    while serving its first committed output (mid-serve, half the
    payload on the wire).  The driver's fetch fails, the coordinator
    forgets the commit and a replacement re-executes the producer —
    exactly one WINNING attempt per shard, zero duplicate commits, and
    the merged output is still byte-identical to the oracle.  Runs with
    an explicit prefetch window of 4 (ISSUE 18): the chaos converges
    under the pipeline too, with the same exactly-once guarantees."""
    corpus = str(tmp_path / "corpus.txt")
    write_corpus(corpus)
    wd = str(tmp_path / "wd")
    stats_json = str(tmp_path / "stats.json")
    cmd = [sys.executable, "-m", "dsi_tpu.cli.shardrun",
           "--engine", "wordcount", "--workers", "2", "--shards", "4",
           "--workdir", wd, "--hosts", "--progress-s", "0.1",
           "--shard-timeout", "5",
           "--fault-worker", "0:mid-serve",
           "--check", "--stats-json", stats_json, corpus]
    env = _env(tmp_path)
    env["DSI_NET_FETCH_WINDOW"] = "4"
    r = subprocess.run(cmd, env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    assert "FAULT: injected crash at mid-serve" in r.stderr
    assert "re-executing" in r.stderr  # the refetch path, loudly
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    assert s["net_fetch_failures"] >= 1
    assert s["net_refetches"] >= 1
    assert s["net_prefetch_window"] == 4  # the pipeline really ran
    assert s["duplicate_commits"] == 0
    # re-execution, not duplication: each shard has exactly one WINNER
    assert s["committed"] == s["shards"] == 4
    assert len(s["winning_attempts"]) == 4
    # total commits may exceed shards (the lost copy was re-committed)
    assert s["commits"] >= 4


@pytest.mark.slow
def test_mrrun_net_chaos_every_worker_dies_serving(tmp_path):
    """Classic-plane chaos: EVERY initial worker dies the first time it
    serves a partition (deterministic mid-serve fault).  Reducers hit
    FetchFailure, the coordinator re-executes the producer maps on
    clean respawns, and parity still holds."""
    corpora = []
    for i in range(3):
        path = str(tmp_path / f"corpus-{i}.txt")
        write_corpus(path, lines=1500, seed=i)
        corpora.append(path)
    wd = str(tmp_path / "wd")
    stats_json = str(tmp_path / "stats.json")
    env = _env(tmp_path)
    env["DSI_FAULT_POINT"] = "mid-serve"
    cmd = [sys.executable, "-m", "dsi_tpu.cli.mrrun",
           "--workers", "2", "--nreduce", "4", "--workdir", wd,
           "--net", "--check", "--stats-json", stats_json,
           "wc"] + corpora
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=420)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    assert "FAULT: injected crash at mid-serve" in r.stderr
    assert "re-executing map" in r.stderr
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    assert s["net_fetch_failures"] >= 1 and s["net_refetches"] >= 1
    assert s["workers_spawned"] > 2  # replacements really spawned
