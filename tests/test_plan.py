"""Plan-layer tests (``dsi_tpu/plan`` + ``device/relay.py``).

What they pin, per the ISSUE-14 contract:

* the relay pack program is byte-exact (device concat == host concat),
  seals at capacity, spills under a budget, and round-trips through
  ``capture``/``restore``;
* a grep → wordcount chain on the device path is BIT-IDENTICAL to the
  staged baseline (host materialization between the stages) across
  depth × device-accumulate × mesh-shards × forced widen inside stage
  2, and moves ZERO intermediate bytes through the host;
* the indexer → df-top-k → postings-join chain matches its staged twin
  in both device-accumulate and host-merge modes, including the
  widen-residue fallback;
* stage commits make the chain resume at the last COMPLETED stage for
  every inter-stage fault point, and a torn stage manifest falls back
  to re-running that stage from its upstream's commit.
"""

import glob
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jax.sharding import NamedSharding, PartitionSpec as P

from dsi_tpu.ckpt.fault import FaultInjected, reset_faults
from dsi_tpu.device.relay import DeviceRelay, HostRelay
from dsi_tpu.obs import get_registry
from dsi_tpu.parallel.shuffle import AXIS, default_mesh
from dsi_tpu.plan import (Plan, PlanError, Stage, grep_wordcount_plan,
                          indexer_join_plan, run_plan)

MESH = None


def mesh():
    global MESH
    if MESH is None:
        MESH = default_mesh(8)
    return MESH


def corpus(n=420, wide_vocab=False, short_lines=False):
    """Matching lines carry 'the' plus a vocabulary; fillers don't."""
    lines = []
    for i in range(n):
        if i % 3 == 0:
            if wide_vocab:
                lines.append("the " + " ".join(
                    f"w{chr(97 + (i * 7 + j) % 26)}"
                    f"{chr(97 + (i * 3 + j) % 26)}q" for j in range(12)))
            elif short_lines:
                lines.append(f"the a{i % 9}")
            else:
                lines.append(f"the quick w{i % 29} fox likes the pond")
        else:
            lines.append("x" if short_lines else
                         f"unrelated filler row{i} content")
    return ("\n".join(lines) + "\n").encode()


def gw_plan(data, **kw):
    kw.setdefault("chunk_bytes", 1 << 9)
    return grep_wordcount_plan("the", data=data, **kw)


# ── relay units ───────────────────────────────────────────────────────


def _dev_chunk(rows, cap):
    """[n_dev, cap] device buffer from per-row byte strings."""
    buf = np.zeros((len(rows), cap), np.uint8)
    kept = np.zeros(len(rows), np.int64)
    for r, b in enumerate(rows):
        buf[r, :len(b)] = np.frombuffer(b, np.uint8)
        kept[r] = len(b)
    sh = NamedSharding(mesh(), P(AXIS, None))
    return jax.device_put(buf, sh), kept


def _drain_rows(relay, n_dev, cap):
    got = [bytearray() for _ in range(n_dev)]
    for b in relay.batches():
        arr = np.asarray(b)
        for r in range(n_dev):
            row = arr[r]
            nz = np.flatnonzero(row)
            end = int(nz[-1]) + 1 if nz.size else 0
            got[r].extend(row[:end].tobytes())
    return [bytes(g) for g in got]


def test_relay_pack_byte_exact_and_seals():
    cap = 64
    n_dev = 8
    stats = {}
    relay = DeviceRelay(mesh(), cap=cap, stats=stats)
    want = [bytearray() for _ in range(n_dev)]
    rng = np.random.default_rng(3)
    for step in range(7):
        rows = []
        for r in range(n_dev):
            n = int(rng.integers(0, 30))
            rows.append(bytes((rng.integers(1, 255, n)).astype(np.uint8)))
            want[r].extend(rows[-1])
        comp, kept = _dev_chunk(rows, cap)
        relay.append(comp, kept)
    assert relay.total_bytes == sum(len(w) for w in want)
    got = _drain_rows(relay, n_dev, cap)
    # Nonzero test bytes → zero-trim reconstruction is exact.
    assert got == [bytes(w) for w in want]
    assert stats["plan_intermediate_bytes"] == 0


def test_relay_spill_budget_counts_and_preserves():
    cap = 32
    n_dev = 8
    stats = {}
    relay = DeviceRelay(mesh(), cap=cap, stats=stats,
                        spill_bytes=n_dev * cap)  # one resident buffer
    want = [bytearray() for _ in range(n_dev)]
    for step in range(6):
        rows = [bytes([65 + step] * 20) for _ in range(n_dev)]
        for r in range(n_dev):
            want[r].extend(rows[r])
        comp, kept = _dev_chunk(rows, cap)
        relay.append(comp, kept)
    assert stats["plan_spilled_bytes"] > 0
    assert stats["plan_intermediate_bytes"] == stats["plan_spilled_bytes"]
    assert _drain_rows(relay, n_dev, cap) == [bytes(w) for w in want]


def test_relay_capture_restore_round_trip():
    cap = 48
    stats = {}
    relay = DeviceRelay(mesh(), cap=cap, stats=stats)
    rows = [b"hello world\n"] * 8
    comp, kept = _dev_chunk(rows, cap)
    relay.append(comp, kept)
    arrays = relay.capture()
    restored = DeviceRelay.restore(mesh(), arrays, cap=cap, stats={})
    assert _drain_rows(restored, 8, cap) == list(rows)
    # The original relay still serves its consumer after the capture.
    assert _drain_rows(relay, 8, cap) == list(rows)


def test_plan_graph_validation():
    p = Plan("t")
    p.add(Stage("a", "grep", pattern="x"))
    with pytest.raises(PlanError):
        p.add(Stage("a", "grep", pattern="x"))  # duplicate
    with pytest.raises(PlanError):
        p.add(Stage("b", "wordcount", deps=["nope"]))  # unknown dep
    with pytest.raises(PlanError):
        Stage("c", "sort")  # unknown kind
    sig = gw_plan(b"abc\n").signature()
    assert sig == gw_plan(b"abc\n").signature()
    assert sig != gw_plan(b"xyz\n").signature()  # data CRC in identity


# ── grep → wordcount parity grid ──────────────────────────────────────


@pytest.mark.parametrize("depth,dacc,shards", [
    (1, False, 0),
    (2, True, 0),
    (2, True, 8),
])
def test_grep_wc_chain_parity(depth, dacc, shards):
    data = corpus()
    kw = dict(depth=depth, device_accumulate=dacc, mesh_shards=shards)
    st_c, st_s = {}, {}
    chained = run_plan(gw_plan(data, **kw), mesh=mesh(), stats=st_c)
    staged = run_plan(gw_plan(data, **kw), mesh=mesh(), staged=True,
                      stats=st_s)
    assert chained.results["grep"] == staged.results["grep"]
    assert chained.final == staged.final
    assert len(chained.final) > 0
    # THE acceptance bar: the device-resident handoff moves zero
    # intermediate bytes through the host; the staged baseline moves
    # the full matching-line materialization.
    assert st_c["plan_intermediate_bytes"] == 0
    assert st_s["plan_intermediate_bytes"] > 0
    assert st_c["plan_handoff"] == "device"
    assert st_s["plan_handoff"] == "host"


def test_grep_wc_forced_widen_inside_stage2(monkeypatch):
    # A tiny device-table rung + a wide matching-line vocabulary force
    # the wordcount stage's widen protocol mid-chain.
    monkeypatch.setenv("DSI_DEVICE_TABLE_CAP", "32")
    data = corpus(wide_vocab=True)
    kw = dict(device_accumulate=True, sync_every=3)
    chained = run_plan(gw_plan(data, **kw), mesh=mesh())
    staged = run_plan(gw_plan(data, **kw), mesh=mesh(), staged=True)
    assert chained.final == staged.final
    assert get_registry().phases("stream").get("widens", 0) >= 1


def test_grep_wc_short_lines_replay_rung():
    # Dense short lines overflow the optimistic l_cap rung: stage 1
    # replays at the wider rung and the emitted bytes stay exact.
    data = corpus(short_lines=True)
    chained = run_plan(gw_plan(data), mesh=mesh())
    staged = run_plan(gw_plan(data), mesh=mesh(), staged=True)
    assert chained.final == staged.final
    assert get_registry().phases("grep").get("replays", 0) >= 1


# ── stage-boundary crash/resume state machine ─────────────────────────


@pytest.mark.parametrize("point,step,resumed", [
    ("plan-stage0-advance", 2, 0),   # mid-stage-1: nothing committed
    ("plan-stage1-advance", 1, 1),   # stage-2 entry: stage 1 committed
    ("plan-stage1-advance", 3, 1),   # mid-stage-2
    ("post-stage-commit", 1, 1),     # right after stage 1's manifest
])
def test_chain_crash_resume_every_fault_point(tmp_path, monkeypatch,
                                              point, step, resumed):
    data = corpus()
    ck = str(tmp_path / "ck")
    want = run_plan(gw_plan(data), mesh=mesh()).final
    monkeypatch.setenv("DSI_FAULT_POINT", point)
    monkeypatch.setenv("DSI_FAULT_STEP", str(step))
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    reset_faults()
    with pytest.raises(FaultInjected):
        run_plan(gw_plan(data), mesh=mesh(), checkpoint_dir=ck)
    monkeypatch.delenv("DSI_FAULT_POINT")
    monkeypatch.delenv("DSI_FAULT_STEP")
    monkeypatch.delenv("DSI_FAULT_MODE")
    st: dict = {}
    res = run_plan(gw_plan(data), mesh=mesh(), checkpoint_dir=ck,
                   resume=True, stats=st)
    assert st["plan_resumed_stages"] == resumed
    assert res.final == want


def test_torn_stage_manifest_falls_back(tmp_path):
    data = corpus()
    ck = str(tmp_path / "ck")
    want = run_plan(gw_plan(data), mesh=mesh()).final
    reset_faults()
    run_plan(gw_plan(data), mesh=mesh(), checkpoint_dir=ck)
    # Tear the FINAL stage's manifest: resume must fall back to the
    # stage-1 commit and re-run only stage 2.
    m = sorted(glob.glob(os.path.join(ck, "stage01-wc",
                                      "manifest-*.json")))[-1]
    with open(m, "r+b") as f:
        f.write(b"GARBAGE")
    st: dict = {}
    res = run_plan(gw_plan(data), mesh=mesh(), checkpoint_dir=ck,
                   resume=True, stats=st)
    assert st["plan_resumed_stages"] == 1
    assert res.final == want


def test_resume_refuses_other_plan(tmp_path):
    from dsi_tpu.ckpt import CheckpointMismatch

    ck = str(tmp_path / "ck")
    run_plan(gw_plan(corpus()), mesh=mesh(), checkpoint_dir=ck)
    with pytest.raises(CheckpointMismatch):
        run_plan(gw_plan(corpus(n=99)), mesh=mesh(), checkpoint_dir=ck,
                 resume=True)


# ── indexer → df-top-k → postings join ────────────────────────────────


DOCS = [f"alpha beta w{i % 7} gamma shared doc{i % 3} tail".encode()
        for i in range(13)]


@pytest.mark.parametrize("dacc", [False, True])
def test_indexer_chain_parity(dacc):
    kw = dict(topk=5, device_accumulate=dacc, u_cap=1 << 8)
    chained = run_plan(indexer_join_plan(DOCS, **kw), mesh=mesh())
    staged = run_plan(indexer_join_plan(DOCS, **kw), mesh=mesh(),
                      staged=True)
    assert chained.results["dftopk"] == staged.results["dftopk"]
    assert chained.final == staged.final
    assert len(chained.final) == 5


def test_indexer_chain_forced_topk_widen_fallback(monkeypatch):
    # A tiny df-table rung forces mid-walk widens whose drains land in
    # the host accumulator: the df-top-k stage must take the exact
    # drain fallback (snapshot alone would miss the host residue).
    monkeypatch.setenv("DSI_DEVICE_TOPK_CAP", "16")
    kw = dict(topk=5, device_accumulate=True, u_cap=1 << 8)
    chained = run_plan(indexer_join_plan(DOCS, **kw), mesh=mesh())
    monkeypatch.delenv("DSI_DEVICE_TOPK_CAP")
    staged = run_plan(indexer_join_plan(DOCS, topk=5, u_cap=1 << 8),
                      mesh=mesh(), staged=True)
    assert chained.results["dftopk"] == staged.results["dftopk"]
    assert chained.final == staged.final


def test_indexer_chain_crash_resume(tmp_path, monkeypatch):
    ck = str(tmp_path / "ck")
    kw = dict(topk=5, device_accumulate=True, u_cap=1 << 8)
    want = run_plan(indexer_join_plan(DOCS, **kw), mesh=mesh())
    monkeypatch.setenv("DSI_FAULT_POINT", "plan-stage1-advance")
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    reset_faults()
    with pytest.raises(FaultInjected):
        run_plan(indexer_join_plan(DOCS, **kw), mesh=mesh(),
                 checkpoint_dir=ck)
    monkeypatch.delenv("DSI_FAULT_POINT")
    monkeypatch.delenv("DSI_FAULT_MODE")
    st: dict = {}
    res = run_plan(indexer_join_plan(DOCS, **kw), mesh=mesh(),
                   checkpoint_dir=ck, resume=True, stats=st)
    assert st["plan_resumed_stages"] == 1
    assert res.results["dftopk"] == want.results["dftopk"]
    assert res.final == want.final


# ── handoff-hook guards ───────────────────────────────────────────────


def test_device_batches_refuses_checkpoint_dir(tmp_path):
    from dsi_tpu.parallel.streaming import WordcountStep

    with pytest.raises(ValueError):
        WordcountStep([], mesh=mesh(), device_batches=iter(()),
                      checkpoint_dir=str(tmp_path / "ck"))


def test_line_sink_refuses_checkpoint_dir(tmp_path):
    from dsi_tpu.parallel.grepstream import GrepStep

    with pytest.raises(ValueError):
        GrepStep([b"x\n"], "x", mesh=mesh(), line_sink=HostRelay(),
                 checkpoint_dir=str(tmp_path / "ck"))
