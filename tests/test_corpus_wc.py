"""Tests for the whole-corpus fused word-count path (ops/corpus_wc.py).

Differential against collections.Counter and the sequential oracle — the
reference's test discipline (test-mr.sh:52-53 sort|cmp parity), on CPU.
"""

import collections
import os
import re

import numpy as np
import pytest

from dsi_tpu.ops.corpus_wc import (
    CorpusResult,
    corpus_wordcount,
    pack_pieces,
    write_corpus_output,
)

PIECE = 1 << 12  # small static shapes for CPU test speed


@pytest.fixture(autouse=True)
def _aot_tmp(tmp_path, monkeypatch):
    # Exercise the AOT cache machinery without littering the repo cache.
    monkeypatch.setenv("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    monkeypatch.setenv("DSI_AOT_QUIET", "1")


def counts_of(res: CorpusResult) -> dict:
    return {w: c for w, (c, _) in res.to_dict().items()}


def oracle(texts) -> dict:
    c = collections.Counter()
    for t in texts:
        c.update(re.findall(r"[A-Za-z]+", t))
    return dict(c)


def test_single_file_counts():
    texts = ["the quick brown fox the quick dog; the!fox\nruns"]
    res = corpus_wordcount([t.encode() for t in texts], piece_size=PIECE)
    assert res is not None
    assert counts_of(res) == oracle(texts)


def test_multi_file_merge():
    texts = ["alpha beta alpha", "beta gamma", "alpha delta gamma gamma"]
    res = corpus_wordcount([t.encode() for t in texts], piece_size=PIECE)
    assert counts_of(res) == oracle(texts)


def test_no_cross_file_token_merge():
    # File 1 ends with letters, file 2 starts with letters: the zero padding
    # between pieces must keep "abc" and "def" separate words.
    res = corpus_wordcount([b"abc", b"def"], piece_size=PIECE)
    assert counts_of(res) == {"abc": 1, "def": 1}


def test_file_larger_than_piece_splits_at_boundaries():
    words = [f"w{i}x" for i in range(3000)]
    text = " ".join(words)  # ~18 KB >> PIECE
    res = corpus_wordcount([text.encode()], piece_size=PIECE)
    assert counts_of(res) == oracle([text])


def test_first_occurrence_positions_and_lengths():
    raw = b"zed apple zed banana"
    res = corpus_wordcount([raw], piece_size=PIECE)
    words = res.words()
    # Row ORDER is grouper-dependent (lexicographic for sort, bucket
    # order for hash — the output writer sorts host-side); positions and
    # lengths are exact either way.
    assert sorted(words) == ["apple", "banana", "zed"]
    by_word = dict(zip(words, zip(res.pos.tolist(), res.lens.tolist())))
    assert by_word["apple"] == (4, 5)
    assert by_word["zed"] == (0, 3)
    # The sort grouper's rows stay lexicographic (the chip path's wire
    # contract).
    res_s = corpus_wordcount([raw], piece_size=PIECE, grouper="sort")
    assert res_s.words() == ["apple", "banana", "zed"]


def test_non_ascii_falls_back():
    assert corpus_wordcount(["héllo".encode()], piece_size=PIECE) is None


def test_word_longer_than_64_falls_back():
    assert corpus_wordcount([b"a" * 70 + b" ok"], piece_size=PIECE) is None


def test_wide_word_ladder():
    text = "w" * 40 + " tiny " + "w" * 40
    res = corpus_wordcount([text.encode()], piece_size=PIECE)
    assert counts_of(res) == {"w" * 40: 2, "tiny": 1}


def test_u_cap_retry():
    words = [f"q{i}z" for i in range(200)]
    text = " ".join(words)
    res = corpus_wordcount([text.encode()], piece_size=PIECE, u_cap=16)
    assert counts_of(res) == oracle([text])


def test_empty_and_letter_free_inputs():
    res = corpus_wordcount([b"", b"123 456 ..."], piece_size=PIECE)
    assert res is not None and counts_of(res) == {}


def test_pack_pieces_reserves_separator_byte():
    buf, n_pieces = pack_pieces([b"x" * (PIECE - 1), b"y"], piece_size=PIECE)
    assert n_pieces == 2
    assert buf[PIECE - 1] == 0  # the guaranteed zero tail byte


def test_ihash_matches_reference(tmp_path):
    from dsi_tpu.mr.worker import ihash

    raw = b"Apple zebra Quilt apple nine ten"
    res = corpus_wordcount([raw], piece_size=PIECE)
    got = res.ihashes().tolist()
    for w, h in zip(res.words(), got):
        assert h == ihash(w), w


def test_output_parity_with_sequential_oracle(tmp_path):
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential
    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(str(tmp_path), n_files=2, file_size=3000)
    raws = [open(p, "rb").read() for p in files]
    oracle_out = str(tmp_path / "mr-correct.txt")
    run_sequential(wc.Map, wc.Reduce, files, oracle_out)

    res = corpus_wordcount(raws, piece_size=PIECE)
    assert res is not None
    write_corpus_output(res, 10, str(tmp_path))

    got = []
    for r in range(10):
        with open(tmp_path / f"mr-out-{r}") as f:
            got.extend(l for l in f if l.strip())
    want = [l for l in open(oracle_out) if l.strip()]
    assert sorted(got) == sorted(want)


def test_within_partition_order_matches_reference(tmp_path):
    # The reference's reduce writes keys in sorted order within each
    # mr-out-<r> (worker.go:124-146); our no-sort path must match that, not
    # just the global sorted merge.
    raw = b"pear kiwi lime pear fig date apple cherry mango plum"
    res = corpus_wordcount([raw], piece_size=PIECE)
    write_corpus_output(res, 10, str(tmp_path))
    for r in range(10):
        with open(tmp_path / f"mr-out-{r}") as f:
            keys = [l.split()[0] for l in f if l.strip()]
        assert keys == sorted(keys)


def test_pack6_encode_roundtrip():
    from dsi_tpu.ops.corpus_wc import pack6_encode

    buf = np.frombuffer(b"The quick brown fox! 00\n" * 8, dtype=np.uint8)
    assert len(buf) % 4 == 0
    packed, table = pack6_encode(buf)
    assert len(packed) == len(buf) * 3 // 4
    # Host-side inverse of the device decode.
    b = packed.reshape(-1, 3).astype(np.uint32)
    v = (b[:, 0] << 16) | (b[:, 1] << 8) | b[:, 2]
    codes = np.stack([(v >> 18) & 63, (v >> 12) & 63,
                      (v >> 6) & 63, v & 63], axis=1).reshape(-1)
    assert np.array_equal(table[codes], buf)


def test_pack6_refuses_wide_alphabet():
    from dsi_tpu.ops.corpus_wc import pack6_encode

    buf = np.arange(256, dtype=np.uint8).repeat(4)
    assert pack6_encode(buf) is None


def test_pack6_path_matches_raw_path():
    texts = ["the quick brown fox; jumps over the lazy dog.\n" * 20,
             "alpha beta gamma delta " * 30]
    raws = [t.encode() for t in texts]
    raw_res = corpus_wordcount(raws, piece_size=PIECE, pack6=False)
    p6_res = corpus_wordcount(raws, piece_size=PIECE, pack6=True)
    assert counts_of(raw_res) == counts_of(p6_res) == oracle(texts)
    assert np.array_equal(raw_res.pos, p6_res.pos)
    assert np.array_equal(raw_res.cnt, p6_res.cnt)


def test_pack6_falls_back_to_raw_when_alphabet_wide():
    # >64 distinct byte values but still ASCII letters + punctuation mix:
    # digits/symbols push the alphabet over 64; counts must still be exact.
    fill = "".join(chr(c) for c in range(33, 112))  # 79 printable symbols
    text = f"alpha {fill} beta alpha"
    res = corpus_wordcount([text.encode()], piece_size=PIECE, pack6=True)
    assert counts_of(res) == oracle([text])


def test_aot_cache_roundtrip_same_result():
    from dsi_tpu.backends import aotcache

    import dsi_tpu.ops.corpus_wc as corpus_mod

    text = b"cache me if you can cache me"
    r1 = corpus_wordcount([text], piece_size=PIECE)
    before = dict(aotcache.stats)
    # Force the next call past BOTH in-process layers (the dispatch
    # lru_cache and the aotcache memo) so it exercises disk-or-compile.
    corpus_mod._get_compiled.cache_clear()
    aotcache._memo.clear()
    r2 = corpus_wordcount([text], piece_size=PIECE)
    assert counts_of(r1) == counts_of(r2)
    if aotcache.stats["loads"] == before["loads"]:
        # Multi-device process (this suite's virtual mesh) or a backend
        # without serialization: the compile path must have served it.
        assert aotcache.stats["compiles"] > before["compiles"]


def test_aot_cache_hits_across_processes(tmp_path):
    """The chip configuration (ONE device per process): a second process
    must load the serialized executable instead of recompiling — VERDICT r2
    task 1a's cross-process criterion, exercised on CPU."""
    import subprocess
    import sys

    child = (
        "import os, jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from dsi_tpu.ops.corpus_wc import corpus_wordcount\n"
        "from dsi_tpu.backends import aotcache\n"
        "res = corpus_wordcount([b'tiny corpus of words tiny'],"
        " piece_size=4096)\n"
        "assert {w: c for w, (c, _) in res.to_dict().items()} =="
        " {'tiny': 2, 'corpus': 1, 'of': 1, 'words': 1}\n"
        "print('loads=%d compiles=%d' % (aotcache.stats['loads'],"
        " aotcache.stats['compiles']))\n"
    )
    env = dict(os.environ)
    env["DSI_AOT_CACHE_DIR"] = str(tmp_path / "aot")
    env["DSI_AOT_QUIET"] = "1"
    env.pop("XLA_FLAGS", None)  # single-device process, like the chip
    env["JAX_PLATFORMS"] = "cpu"
    outs = []
    for _ in range(2):
        p = subprocess.run([sys.executable, "-c", child], env=env,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stderr[-2000:]
        outs.append(p.stdout.strip().splitlines()[-1])
    assert outs[0] == "loads=0 compiles=1"
    assert outs[1] == "loads=1 compiles=0"


def test_executable_persisted_probe_mirrors_run_shapes(tmp_path):
    """corpus_executable_persisted must hit the exact key a real run
    persists — including exactness_retry's rung-0 capacity, which caps
    u_cap by the buffer-length hard bound (a drifted mirror silently
    reports False forever and the bench would skip a warmed pack6
    transport / never trust its own cache).  Single-device subprocess:
    persistence is disabled on the 8-device test mesh by design."""
    import subprocess
    import sys

    child = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from dsi_tpu.ops.corpus_wc import (corpus_executable_persisted,\n"
        "                                   corpus_wordcount)\n"
        "raws = [b'the quick brown fox ' * 500,\n"
        "        b'jumps over the lazy dog ' * 400]\n"
        "assert not corpus_executable_persisted(raws)\n"
        "assert not corpus_executable_persisted(raws, pack6=True)\n"
        "corpus_wordcount(raws)\n"
        "corpus_wordcount(raws, pack6=True)\n"
        "assert corpus_executable_persisted(raws)\n"
        "assert corpus_executable_persisted(raws, pack6=True)\n"
        "assert not corpus_executable_persisted([b'word ' * 99999])\n"
        "print('probe-ok')\n"
    )
    env = dict(os.environ)
    env["DSI_AOT_CACHE_DIR"] = str(tmp_path / "aot")
    env["DSI_AOT_QUIET"] = "1"
    env.pop("XLA_FLAGS", None)  # single-device process, like the chip
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-2000:]
    assert p.stdout.strip().splitlines()[-1] == "probe-ok"


@pytest.mark.parametrize("mode", ["async", "sync"])
def test_upload_modes_identical(mode, monkeypatch):
    """DSI_UPLOAD_MODE selects transfer geometry only — results must be
    byte-identical either way, and xfer telemetry must record the run."""
    from dsi_tpu.ops import xfer

    monkeypatch.setenv("DSI_UPLOAD_MODE", mode)
    xfer.stats["upload_s"] = 0.0
    texts = ["upload mode parity check one two two three three three"]
    res = corpus_wordcount([t.encode() for t in texts], piece_size=PIECE)
    assert counts_of(res) == oracle(texts)
    assert xfer.stats["upload_mode"] == mode
    assert xfer.stats["upload_s"] > 0.0
