"""Runtime lock-order validator (dsi_tpu/analysis/lockcheck.py).

The acceptance bar: a synthetic ABBA deadlock is caught (raised before
blocking, both chains named), the daemon's real lock idioms — Condition
built over a tracked Lock, cv.wait with timeout, RLock reentrancy,
stdlib queue — all compose cleanly, and the coordinator's full
lock/condvar machinery runs green under the validator (the in-process
twin of the CI serve smoke's ``DSI_LOCKCHECK=1``)."""

import threading
import time

import pytest

from dsi_tpu.analysis import lockcheck


@pytest.fixture()
def tracked():
    lockcheck.install()
    try:
        yield
    finally:
        lockcheck.uninstall()


def test_abba_cycle_raises_before_blocking(tracked):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:  # establishes A -> B
            pass
    with b:
        with pytest.raises(lockcheck.LockOrderError) as ei:
            b2 = a  # the inversion: B held, acquiring A
            b2.acquire()
    msg = str(ei.value)
    assert "cycle" in msg and "->" in msg
    assert lockcheck.violations(), "violation not recorded"
    # Single-threaded throughout: the validator flags the SCHEDULE
    # hazard, it does not need the deadlock to actually happen.


def test_consistent_order_never_flags(tracked):
    a = threading.Lock()
    b = threading.Lock()
    c = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                with c:
                    pass
    assert lockcheck.violations() == []
    g = lockcheck.order_graph()
    assert any(g.values()), "edges should have been recorded"


def test_condition_wait_and_rlock_compose(tracked):
    mu = threading.Lock()
    cv = threading.Condition(mu)
    hits = []

    def waiter():
        with cv:
            while not hits:
                if cv.wait(timeout=5.0):
                    break

    t = threading.Thread(target=waiter)
    t.start()
    with cv:
        hits.append(1)
        cv.notify_all()
    t.join(timeout=10.0)
    assert not t.is_alive()
    # cv.wait released the tracked lock: this thread could take it.
    r = threading.RLock()
    with r:
        with r:  # reentrant: no self-deadlock, no bogus edge
            pass
    assert lockcheck.violations() == []


def test_condition_wait_over_reentrant_rlock_fully_releases(tracked):
    """Regression (review finding): Condition.wait over an RLock held
    at count 2 must release ALL levels — without _release_save/
    _acquire_restore on the wrapper, Condition's fallback released one
    level, the underlying lock stayed held through the wait, and the
    validator itself manufactured a deadlock."""
    cv = threading.Condition(threading.RLock())
    hits = []

    def waiter():
        with cv:
            with cv:  # re-entrant: count 2 at the wait
                while not hits:
                    if cv.wait(timeout=5.0):
                        break
                hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 5.0
    notified = False
    while time.monotonic() < deadline:
        # the notifier must be able to take the lock DURING the wait
        if cv.acquire(timeout=0.1):
            try:
                hits.append(1)
                cv.notify_all()
                notified = True
            finally:
                cv.release()
            break
    t.join(timeout=10.0)
    assert notified, "underlying RLock stayed held through cv.wait"
    assert not t.is_alive() and "woke" in hits
    assert lockcheck.violations() == []


def test_same_site_nesting_is_not_a_cycle(tracked):
    def make():
        return threading.Lock()  # one creation site, many instances

    x, y = make(), make()
    with x:
        with y:  # same lock class nested: recorded, never raised
            pass
    with y:
        with x:
            pass
    assert lockcheck.violations() == []


def test_uninstall_restores_and_tracked_locks_degrade(tracked):
    held_before = threading.Lock()
    lockcheck.uninstall()
    assert not lockcheck.installed()
    # a wrapper created while installed still locks correctly
    with held_before:
        assert held_before.locked()
    assert not held_before.locked()
    lockcheck.install()  # the fixture's uninstall stays balanced


def test_coordinator_runs_green_under_validator(tracked, tmp_path):
    """The real control plane (mu + deadline Condition + watchdog
    thread + journal) under the validator: assignment, completion,
    requeue arming, and close() must produce zero violations — the
    in-process twin of the CI daemon smoke's DSI_LOCKCHECK=1."""
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator

    files = [str(tmp_path / f"in-{i}.txt") for i in range(3)]
    for f in files:
        open(f, "w").write("a b c\n")  # dsicheck: allow[raw-write] test input
    c = Coordinator(files, n_reduce=2,
                    config=JobConfig(n_reduce=2, task_timeout_s=30.0,
                                     workdir=str(tmp_path)))
    try:
        for i in range(3):
            r = c.request_task({"WorkerId": "w0"})
            assert r["TaskStatus"] == 0
            c.map_complete({"TaskNumber": r["CMap"], "WorkerId": "w0"})
        for i in range(2):
            r = c.request_task({"WorkerId": "w0"})
            assert r["TaskStatus"] == 1
            c.reduce_complete({"TaskNumber": r["CReduce"],
                               "WorkerId": "w0"})
        assert c.done()
        assert c.straggler_suspects() == {}
    finally:
        c.close()
    assert lockcheck.violations() == []
