"""Mesh-sharded device services (ISSUE 7): the paper's shuffle as an
on-device all-to-all.

The parity bar is the tentpole's contract: with ``mesh_shards`` the fold
programs route every key to ``ihash(key) % n_shards`` over the mesh
before merging, and the results must stay BIT-IDENTICAL to the host-
merge path across engine × depth × forced per-shard widen × crash-
resume.  The grid here pins that, plus the per-shard widen protocol's
central claim — a hot shard (skewed key distribution) drains, reallocs
and re-folds ALONE — and the cross-degree resume drain path recorded in
the checkpoint manifest (``mesh_shards`` field).

The shard-routing device-vs-host ihash property lives with the other
hypothesis properties in tests/test_property_fuzz.py.
"""

import itertools
import string

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.ckpt import FaultInjected, reset_faults
from dsi_tpu.ops.meshroute import host_shard_of
from dsi_tpu.parallel.grepstream import (grep_host_oracle, grep_streaming,
                                         indexer_streaming)
from dsi_tpu.parallel.shuffle import default_mesh
from dsi_tpu.parallel.streaming import wordcount_streaming
from dsi_tpu.parallel.tfidf import tfidf_sharded

N_SHARDS = 8


def _mesh():
    return default_mesh(N_SHARDS)


WC_TEXT = ("alpha beta gamma delta the fox jumps over lazy dogs "
           "epsilon zeta eta theta iota kappa " * 2500).encode()  # ~7 steps
WC_CHUNK = 1 << 12

_GREP_LINES = [b"ab " * (i % 5) + b"line" + str(i).encode()
               for i in range(2500)]
GREP_TEXT = b"\n".join(_GREP_LINES) + b"\n"

IDX_DOCS = [("doc%d alpha beta w%d w%d common" % (i, i % 7, i % 3)).encode()
            for i in range(20)]


def _run_wc(mesh_shards=0, depth=2, stats=None, ckpt=None, resume=False,
            text=WC_TEXT, **kw):
    reset_faults()
    return wordcount_streaming(
        [text], mesh=_mesh(), n_reduce=10, chunk_bytes=WC_CHUNK,
        u_cap=256, depth=depth, mesh_shards=mesh_shards, sync_every=2,
        checkpoint_dir=ckpt, checkpoint_every=2, resume=resume,
        pipeline_stats=stats, **kw)


# ── the parity grid: engine × depth × mesh ─────────────────────────────


@pytest.mark.parametrize("depth", [1, 3])
def test_wordcount_mesh_bit_identical(depth):
    base = _run_wc(depth=1)  # the depth=1 host-merge parity anchor
    assert base is not None
    st = {}
    got = _run_wc(mesh_shards=N_SHARDS, depth=depth, stats=st)
    assert got == base
    assert st["mesh_shards"] == N_SHARDS
    assert st["folds"] > 0 and st["pull_bytes"] > 0


@pytest.mark.parametrize("depth", [1, 3])
def test_grep_mesh_bit_identical_and_premerged_hist(depth):
    want = grep_host_oracle([GREP_TEXT], "ab", topk=8)
    st, st0 = {}, {}
    base = grep_streaming([GREP_TEXT], "ab", mesh=_mesh(),
                          chunk_bytes=1 << 11, depth=1,
                          device_accumulate=True, sync_every=2, topk=8,
                          pipeline_stats=st0)
    got = grep_streaming([GREP_TEXT], "ab", mesh=_mesh(),
                         chunk_bytes=1 << 11, depth=depth,
                         mesh_shards=N_SHARDS, sync_every=2, topk=8,
                         pipeline_stats=st)
    assert base == want and got == want
    # The histogram pull pre-merges on device: one [slots] vector per
    # pull instead of n_dev partials — 1/n_dev the bytes per hist pull.
    assert st["hist_pulls"] == st0["hist_pulls"] > 0
    assert 0 < st["pull_bytes"] < st0["pull_bytes"]
    assert st["mesh_shards"] == N_SHARDS


def test_indexer_mesh_bit_identical():
    base = indexer_streaming(IDX_DOCS, mesh=_mesh(), n_reduce=10,
                             u_cap=1 << 8, depth=1, topk=8)
    st = {}
    got = indexer_streaming(IDX_DOCS, mesh=_mesh(), n_reduce=10,
                            u_cap=1 << 8, depth=2,
                            mesh_shards=N_SHARDS, topk=8, stats=st)
    # Postings (incl. per-word order) AND df top-k, bit-for-bit.
    assert got == base
    assert st["mesh_shards"] == N_SHARDS and st["appends"] > 0


def test_tfidf_mesh_bit_identical():
    base = tfidf_sharded(IDX_DOCS, mesh=_mesh(), n_reduce=10,
                         u_cap=1 << 8, depth=1)
    st = {}
    got = tfidf_sharded(IDX_DOCS, mesh=_mesh(), n_reduce=10,
                        u_cap=1 << 8, depth=2, mesh_shards=N_SHARDS,
                        wave_stats=st)
    assert got == base
    assert st["mesh_shards"] == N_SHARDS and st["appends"] > 0


# ── the per-shard widen protocol ───────────────────────────────────────


def _skewed_text(hot_shard: int, n_hot: int = 300, n_cold: int = 8):
    """A corpus whose vocabulary concentrates on ONE shard's hash range
    — the adversarial key distribution of the tentpole's acceptance
    criterion."""
    hot, cold = [], []
    for t in itertools.product(string.ascii_lowercase, repeat=4):
        w = "".join(t).encode()
        (hot if host_shard_of(w, N_SHARDS) == hot_shard else cold).append(w)
        if len(hot) >= n_hot and len(cold) >= n_cold:
            break
    line = b" ".join(hot[:n_hot] + cold[:n_cold]) + b"\n"
    return line * 24, hot[:n_hot], cold[:n_cold]


def test_hot_shard_widens_alone(monkeypatch):
    """Skewed keys + a forced-tiny table rung: ONLY the hot shard pays
    the drain→realloc×4→re-fold — its counter advances, every cold
    shard's stays zero — and the result is still bit-identical."""
    hot_shard = 3
    text, hot, cold = _skewed_text(hot_shard)
    base = _run_wc(depth=1, text=text)
    assert base is not None
    monkeypatch.setenv("DSI_DEVICE_TABLE_CAP", "64")
    st = {}
    got = _run_wc(mesh_shards=N_SHARDS, depth=2, stats=st, text=text)
    monkeypatch.delenv("DSI_DEVICE_TABLE_CAP")
    assert got == base
    widens = st["shard_widens"]
    assert widens[hot_shard] >= 1, widens
    assert sum(widens) == widens[hot_shard], \
        f"cold shards widened too: {widens}"
    assert st["shard_imbalance"] > 2.0  # the skew is visible


def test_grep_topk_mesh_forced_widen(monkeypatch):
    """The grep candidate table under a forced-tiny rung: per-shard
    widens fire (line keys hash-spread, so several shards may be hot)
    and the exact top-k survives."""
    want = grep_host_oracle([GREP_TEXT], "ab", topk=8)
    monkeypatch.setenv("DSI_DEVICE_TOPK_CAP", "16")
    st = {}
    got = grep_streaming([GREP_TEXT], "ab", mesh=_mesh(),
                         chunk_bytes=1 << 11, depth=2,
                         mesh_shards=N_SHARDS, sync_every=2, topk=8,
                         pipeline_stats=st)
    monkeypatch.delenv("DSI_DEVICE_TOPK_CAP")
    assert got == want
    assert st["widens"] >= 1
    assert sum(st["shard_widens"]) >= st["widens"]


# ── crash-resume × mesh ────────────────────────────────────────────────


def _fault(monkeypatch, point, step):
    monkeypatch.setenv("DSI_FAULT_MODE", "raise")
    monkeypatch.setenv("DSI_FAULT_POINT", point)
    monkeypatch.setenv("DSI_FAULT_STEP", str(step))


def _clear_fault(monkeypatch):
    for k in ("DSI_FAULT_MODE", "DSI_FAULT_POINT", "DSI_FAULT_STEP"):
        monkeypatch.delenv(k, raising=False)


@pytest.mark.parametrize("point,at", [("mid-fold", 4), ("pre-sync", 2)])
def test_mesh_crash_resume_bit_identical(monkeypatch, tmp_path, point, at):
    base = _run_wc(depth=1)
    ck = str(tmp_path / "ck")
    _fault(monkeypatch, point, at)
    with pytest.raises(FaultInjected):
        _run_wc(mesh_shards=N_SHARDS, ckpt=ck)
    _clear_fault(monkeypatch)
    st = {}
    got = _run_wc(mesh_shards=N_SHARDS, ckpt=ck, resume=True, stats=st)
    assert got == base
    # Resume must actually have engaged: the fault fires after the
    # checkpoint at confirmed step 2 (checkpoint_every=2), so a restored
    # cursor is guaranteed, not merely possible.
    assert st.get("resume_cursor", 0) > 0


def test_resume_across_sharding_degrees(monkeypatch, tmp_path):
    """The manifest records the image's sharding degree; resuming onto a
    DIFFERENT degree re-enters through the drain path (the image's
    merged rows flow to the host accumulator) and stays bit-identical
    — both directions."""
    base = _run_wc(depth=1)
    for crash_shards, resume_shards in ((0, N_SHARDS), (N_SHARDS, 0)):
        ck = str(tmp_path / f"ck{crash_shards}")
        _fault(monkeypatch, "mid-fold", 4)
        with pytest.raises(FaultInjected):
            _run_wc(mesh_shards=crash_shards, ckpt=ck,
                    device_accumulate=True)
        _clear_fault(monkeypatch)
        st = {}
        got = _run_wc(mesh_shards=resume_shards, ckpt=ck, resume=True,
                      device_accumulate=True, stats=st)
        assert got == base, (crash_shards, resume_shards)
        # The mid-fold fault at step 4 fires after the checkpoint at
        # confirmed step 2 (checkpoint_every=2), so resume MUST engage
        # and MUST take the cross-degree drain path.  Direct indexing:
        # `resharded_resume`'s value is the checkpoint's old degree —
        # legitimately 0 in the host-merge→mesh direction — so key
        # PRESENCE, not truthiness, is the "a reshard ran" signal.
        assert st.get("resume_cursor", 0) > 0
        assert st["resharded_resume"] == crash_shards


def test_mesh_shards_exceeding_mesh_refuses():
    from dsi_tpu.device.table import DeviceTable
    from dsi_tpu.parallel.merge import PackedCounts

    with pytest.raises(ValueError):
        DeviceTable(_mesh(), kk=4, cap=64, acc=PackedCounts(),
                    mesh_shards=N_SHARDS + 1)
