"""Race-soak of the REAL host control plane.

The reference builds every binary with the Go race detector and runs the
full job repeatedly to amplify flakes (``main/test-mr.sh:10,19-22``,
``main/test-mr-many.sh:15-22``).  Python has no tsan, so the analogue is a
high-contention soak: many workers x tiny tasks x a task timeout on the
order of task duration, repeated, with output parity asserted every trial —
the duplicate-execution, requeue-vs-complete, and dial-under-load races all
fire here if they exist (VERDICT r1 items 2 and 9).
"""

from __future__ import annotations

import os
import textwrap

import pytest

from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output, run_distributed_threads

N_TRIALS = 20

SLOW_WC = textwrap.dedent(
    """
    '''wc with a deterministic per-task stall, sized to straddle the
    requeue timeout so some map tasks get reassigned mid-flight.'''
    import time
    import zlib

    from dsi_tpu.apps.wc import Map as _Map, Reduce

    def Map(filename, contents):
        # Deterministic stall in [0, 0.3) s keyed by the split name: some
        # tasks finish well inside the 0.2 s timeout, some blow through it.
        time.sleep((zlib.crc32(filename.encode()) % 300) / 1000.0)
        return _Map(filename, contents)
    """)


@pytest.mark.slow
def test_many_worker_tiny_task_race_soak(tmp_path):
    corpus_dir = tmp_path / "inputs"
    files = ensure_corpus(str(corpus_dir), n_files=12, file_size=2_000)
    plugin = tmp_path / "slow_wc.py"
    plugin.write_text(SLOW_WC)
    want = oracle_output("wc", files, str(tmp_path))

    for trial in range(N_TRIALS):
        wd = tmp_path / f"trial-{trial}"
        os.makedirs(wd)
        run_distributed_threads(str(plugin), files, str(wd), n_workers=8,
                                n_reduce=6, timeout_s=60.0,
                                task_timeout_s=0.2)
        assert merged_output(str(wd)) == want, f"parity broke in trial {trial}"
