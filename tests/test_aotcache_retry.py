"""aotcache transient-compile retry: UNAVAILABLE RPC deaths retry in
process (bounded), deterministic failures raise immediately."""

import os

import numpy as np
import pytest

pytest.importorskip("jax")

from dsi_tpu.backends import aotcache


class _FlakyJit:
    """Stands in for jax.jit(fn): .lower(...).compile() fails with a
    transient error ``fails`` times, then compiles for real."""

    def __init__(self, real_jitted, fails: int, msg: str):
        self.real = real_jitted
        self.left = fails
        self.msg = msg
        self.attempts = 0

    def lower(self, *a, **k):
        outer = self

        class _Lowered:
            def compile(self):
                outer.attempts += 1
                if outer.left > 0:
                    outer.left -= 1
                    raise RuntimeError(outer.msg)
                return outer.real.lower(*a, **k).compile()

        return _Lowered()


def _flaky_compile(monkeypatch, fails, msg, retries=None):
    import jax

    # No pause between attempts, and skip the tunnel-port probe (retry
    # gating on a live tunnel is for the axon platform, not CI).
    monkeypatch.setenv("DSI_COMPILE_RETRY_PAUSE_S", "0")
    monkeypatch.setenv("DSI_TUNNEL_PROBE_PORT", "0")
    if retries is not None:
        monkeypatch.setenv("DSI_COMPILE_RETRIES", str(retries))
    flaky = {}
    real_jit = jax.jit

    def fake_jit(fn, **kw):
        flaky["jit"] = _FlakyJit(real_jit(fn, **kw), fails, msg)
        return flaky["jit"]

    monkeypatch.setattr(jax, "jit", fake_jit)
    x = np.arange(8, dtype=np.int32)
    compiled = aotcache.cached_compile(
        f"retrytest_{fails}_{msg[:12]}_{retries}", lambda v: v + 1, (x,),
        persist=False)
    return flaky["jit"], compiled, x


def test_transient_unavailable_retries(monkeypatch):
    jit, compiled, x = _flaky_compile(
        monkeypatch, fails=2,
        msg="UNAVAILABLE: remote_compile: Network Error: Unexpected EOF")
    assert jit.attempts == 3  # 2 failures + 1 success
    np.testing.assert_array_equal(np.asarray(compiled(x)), x + 1)


def test_transient_budget_exhausted_raises(monkeypatch):
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        _flaky_compile(
            monkeypatch, fails=5,
            msg="UNAVAILABLE: transport: Connection refused", retries=1)


def test_deterministic_error_raises_immediately(monkeypatch):
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        jit, _, _ = _flaky_compile(
            monkeypatch, fails=5, msg="RESOURCE_EXHAUSTED: out of memory")


class _PoisonedExe:
    """Deserializes fine, dies at execution — the 2026-07-31 landmine."""

    calls = 0

    def __call__(self, *a):
        type(self).calls += 1
        raise RuntimeError(
            "NOT_FOUND: Buffer Definition Event: Function "
            "concatenate.35_kernel not found (type id: 1)")


def test_loaded_executable_exec_failure_heals(tmp_path):
    """A loaded entry whose first execution fails is evicted, marked, and
    recompiled in-process; the caller sees only the correct result.
    (_verify_first_call is unit-tested directly: the test mesh has 8
    virtual devices, which disables disk persistence in cached_compile.)"""
    import jax

    x = np.arange(16, dtype=np.int32)
    path = str(tmp_path / "poisontest-abc.aot")
    with open(path, "w") as f:
        f.write("poisoned-bytes")
    with jax.default_device(jax.devices()[0]):
        jitted = jax.jit(lambda v: v * 2)
        wrapped = aotcache._verify_first_call(
            _PoisonedExe(), path, "poisontest", jitted, (x,), {})
        out = wrapped(x)
        np.testing.assert_array_equal(np.asarray(out), x * 2)
        assert _PoisonedExe.calls == 1
        assert not os.path.exists(path), "poisoned entry not evicted"
        assert os.path.exists(path + ".execfail"), "no poison marker"
        # Marked entries are neither loaded nor re-saved.
        assert aotcache._try_load(path) is None
        aotcache._try_save(path, None, "poisontest")
        assert not os.path.exists(path)
        # Second call goes straight through (verified).
        out2 = wrapped(x)
        np.testing.assert_array_equal(np.asarray(out2), x * 2)
        assert _PoisonedExe.calls == 1


def test_loaded_executable_unavailable_not_marked(tmp_path):
    """UNAVAILABLE during the first call is a tunnel hiccup: re-raised,
    no eviction, no poison marker."""
    import jax

    class _Hiccup:
        def __call__(self, *a):
            raise RuntimeError("UNAVAILABLE: transport: Unexpected EOF")

    x = np.arange(16, dtype=np.int32)
    path = str(tmp_path / "hiccuptest-abc.aot")
    with open(path, "w") as f:
        f.write("entry-bytes")
    wrapped = aotcache._verify_first_call(
        _Hiccup(), path, "hiccuptest", jax.jit(lambda v: v * 2), (x,), {})
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        wrapped(x)
    assert os.path.exists(path), "entry must not be evicted on UNAVAILABLE"
    assert not os.path.exists(path + ".execfail")
