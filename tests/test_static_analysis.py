"""dsicheck: every rule proven on a known-bad fixture, the tree proven
clean, and the CLI contract pinned.

The fixture files under ``tests/fixtures/dsicheck/`` carry
``# EXPECT: <rule>`` trailing markers on each line a rule must fire on;
the tests here diff the engine's findings against those markers
exactly — a rule that stops firing (or starts over-firing) fails the
fixture test, and a new violation anywhere in ``dsi_tpu/`` fails the
clean-tree test.  No jax required anywhere in this file: the analysis
plane must work mid-outage and in a bare CI interpreter.
"""

import os
import re
import subprocess
import sys

import pytest

from dsi_tpu.analysis import core
from dsi_tpu.analysis.rules import all_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "dsicheck")
DSICHECK = os.path.join(REPO, "scripts", "dsicheck.py")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z\-]+)")


def expected_markers(path):
    """{(line, rule), ...} from the fixture's # EXPECT: comments."""
    out = set()
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            m = _EXPECT_RE.search(line)
            if m:
                out.add((i, m.group(1)))
    return out


def run_fixture(name):
    path = os.path.join(FIXTURES, name)
    findings = core.run_project(REPO, [path])
    got = {(f.line, f.rule) for f in findings if not f.suppressed}
    return got, findings


@pytest.mark.parametrize("fixture", [
    "bad_donation.py",
    "bad_rawwrite.py",
    "bad_lockguard.py",
    "bad_span.py",
    "bad_schema.py",
    "bad_jitpure.py",
])
def test_rule_fires_exactly_on_marked_lines(fixture):
    """Each known-bad fixture produces exactly its marked findings —
    right rule, right file:line, nothing extra (over-firing is noise
    that would get the gate ignored)."""
    got, _ = run_fixture(fixture)
    want = expected_markers(os.path.join(FIXTURES, fixture))
    assert want, f"{fixture} has no EXPECT markers"
    assert got == want, (
        f"{fixture}: findings != markers\n"
        f"  missing: {sorted(want - got)}\n  extra: {sorted(got - want)}")


def test_every_rule_has_a_firing_fixture():
    """The catalogue is closed under proof: a rule without a fixture
    that fires it is an unproven gate."""
    fired = set()
    for name in os.listdir(FIXTURES):
        if name.endswith(".py"):
            got, _ = run_fixture(name)
            fired.update(rule for _ln, rule in got)
    assert fired == {r.rule_id for r in all_rules()}


def test_suppression_comments_downgrade_findings():
    """allow[] on the same line, via a multi-line comment block above,
    and allow[all] all suppress; nothing unsuppressed leaks."""
    got, findings = run_fixture("suppressed_ok.py")
    assert got == set(), got
    sup = [f for f in findings if f.suppressed]
    assert len(sup) == 3
    assert {f.rule for f in sup} == {"raw-write"}


def test_trailing_allow_does_not_leak_to_next_line(tmp_path):
    """Regression (review finding): a trailing annotation suppresses
    ITS line only — an unannotated violation on the next line still
    fails the gate."""
    bad = tmp_path / "leak.py"
    bad.write_text(
        "def f(p, q, data):\n"
        "    open(p, 'wb').write(data)  # dsicheck: allow[raw-write] x\n"
        "    open(q, 'wb').write(data)\n")
    findings = core.run_project(str(tmp_path), [str(bad)])
    assert [(f.line, f.suppressed) for f in findings
            if f.rule == "raw-write"] == [(2, True), (3, False)]


def test_unparsable_file_is_a_finding_not_a_crash(tmp_path):
    """Regression (review finding): a syntax-error file surfaces as a
    non-suppressible parse-error finding with file:line — the CI gate
    fails with evidence, never a traceback."""
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n    pass\n")
    findings = core.run_project(str(tmp_path), [str(bad)])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "parse-error" and not f.suppressed
    assert f.path.endswith("broken.py") and f.line == 1
    # and through the CLI: exit 1, still valid --json
    p = subprocess.run([sys.executable, DSICHECK, "--json", str(bad)],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 1, p.stdout + p.stderr
    import json

    doc = json.loads(p.stdout)
    assert doc["findings"][0]["rule"] == "parse-error"


def test_tree_is_clean():
    """THE gate: zero unsuppressed findings over dsi_tpu/ — every
    violation the rules can see today is fixed or annotated, so any
    future finding is a regression introduced by that change."""
    findings = core.run_project(REPO, [os.path.join(REPO, "dsi_tpu")])
    unsup = [f for f in findings if not f.suppressed]
    assert unsup == [], "\n".join(f.render() for f in unsup)
    # The suppressed inventory is part of the contract: it only ever
    # changes deliberately, with a reviewed reason next to each site.
    # 13: +1 for the stage-host console capture (cli/planrun.py — a
    # subprocess stdout handle held open for the child's lifetime, so
    # atomic_write's rename-on-close contract cannot apply)
    # 17: +4 for the replicated control plane — replica/rlog.py's
    # append + in-place-truncation pair (per-record CRC framing IS the
    # durability story, same idiom as mr/journal.py), the replicad
    # spec file (replica/driver.py — consumed once by a child the
    # parent waits on), and mrrun's --stats-json parse surface
    sup = [f for f in findings if f.suppressed]
    assert len(sup) <= 17, (
        "suppression inventory grew suspiciously large — are "
        "annotations being used where a fix belongs?\n"
        + "\n".join(f.render() for f in sup))


def test_cli_exit_codes_and_json():
    env = dict(os.environ)
    # clean tree -> 0
    p = subprocess.run([sys.executable, DSICHECK], capture_output=True,
                       text=True, env=env, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "0 finding(s)" in p.stdout
    # fixtures -> 1, and --json round-trips
    p = subprocess.run([sys.executable, DSICHECK, "--json", FIXTURES],
                       capture_output=True, text=True, env=env,
                       timeout=120)
    assert p.returncode == 1
    import json

    doc = json.loads(p.stdout)
    assert doc["findings"] and doc["suppressed"]
    assert {"path", "line", "col", "rule", "message"} <= \
        set(doc["findings"][0])
    # --list-rules names all six
    p = subprocess.run([sys.executable, DSICHECK, "--list-rules"],
                       capture_output=True, text=True, env=env,
                       timeout=120)
    assert p.returncode == 0
    for rid in ("donation-after-use", "raw-write", "lock-guard",
                "span-discipline", "metric-schema", "jit-purity"):
        assert rid in p.stdout
    # unknown rule -> usage error
    p = subprocess.run([sys.executable, DSICHECK, "--rules", "nope"],
                       capture_output=True, text=True, env=env,
                       timeout=120)
    assert p.returncode == 2


def test_rule_selection():
    findings = core.run_project(
        REPO, [os.path.join(FIXTURES, "bad_rawwrite.py")],
        [r for r in all_rules() if r.rule_id == "jit-purity"])
    assert findings == []


def test_engine_needs_no_third_party_imports():
    """dsicheck must run on a bare interpreter (CI gate job, outage
    boxes): importing the whole analysis plane pulls no jax/numpy."""
    code = ("import sys; "
            "sys.modules['jax'] = None; sys.modules['numpy'] = None; "
            "import dsi_tpu.analysis, dsi_tpu.analysis.rules, "
            "dsi_tpu.analysis.lockcheck; print('ok')")
    p = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0 and "ok" in p.stdout, p.stderr
