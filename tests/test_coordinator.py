"""Coordinator state-machine unit tests.

The reference has no unit tests at all (SURVEY.md §4); these pin the scheduler
semantics of ``mr/coordinator.go``: map-before-reduce barrier, waiting states,
straggler re-queue, unique-transition completion counting (the documented
double-count fix), and done().
"""

import time

from dsi_tpu.config import JobConfig
from dsi_tpu.mr.coordinator import Coordinator
from dsi_tpu.mr.types import TaskStatus


def mk(files=3, n_reduce=2, timeout=10.0):
    return Coordinator([f"in-{i}" for i in range(files)], n_reduce,
                       JobConfig(n_reduce=n_reduce, task_timeout_s=timeout))


def test_assigns_all_maps_then_waits():
    c = mk(files=2, n_reduce=1)
    r1 = c.request_task({})
    r2 = c.request_task({})
    assert r1["TaskStatus"] == TaskStatus.MAP and r2["TaskStatus"] == TaskStatus.MAP
    assert {r1["CMap"], r2["CMap"]} == {0, 1}
    assert r1["Filename"] == "in-0" and r1["NReduce"] == 1
    # all maps assigned but incomplete -> WAITING (coordinator.go:58-60)
    assert c.request_task({})["TaskStatus"] == TaskStatus.WAITING


def test_map_barrier_gates_reduce():
    c = mk(files=2, n_reduce=2)
    c.request_task({}); c.request_task({})
    c.map_complete({"TaskNumber": 0})
    # one map still outstanding -> still no reduce (coordinator.go:47,79)
    assert c.request_task({})["TaskStatus"] == TaskStatus.WAITING
    c.map_complete({"TaskNumber": 1})
    r = c.request_task({})
    assert r["TaskStatus"] == TaskStatus.REDUCE
    assert r["NMap"] == 2


def test_done_only_after_all_reduces():
    c = mk(files=1, n_reduce=2)
    c.request_task({}); c.map_complete({"TaskNumber": 0})
    c.request_task({}); c.request_task({})
    assert not c.done()
    c.reduce_complete({"TaskNumber": 0})
    assert not c.done()
    c.reduce_complete({"TaskNumber": 1})
    assert c.done()
    assert c.request_task({})["TaskStatus"] == TaskStatus.DONE


def test_straggler_requeue():
    # presumed-dead-by-timeout: task re-queued after task_timeout_s
    # (coordinator.go:70-77)
    c = mk(files=1, n_reduce=1, timeout=0.15)
    r = c.request_task({})
    assert r["TaskStatus"] == TaskStatus.MAP
    assert c.request_task({})["TaskStatus"] == TaskStatus.WAITING
    time.sleep(0.4)
    r2 = c.request_task({})
    assert r2["TaskStatus"] == TaskStatus.MAP and r2["CMap"] == 0


def test_completion_beats_requeue_race():
    # if completion lands before the timer fires, the task must NOT be requeued
    c = mk(files=1, n_reduce=1, timeout=0.15)
    c.request_task({})
    c.map_complete({"TaskNumber": 0})
    time.sleep(0.4)
    r = c.request_task({})
    assert r["TaskStatus"] == TaskStatus.REDUCE  # straight to reduce phase


def test_duplicate_completion_not_double_counted():
    # The reference double-counts duplicate completion RPCs
    # (coordinator.go:30-31) which can prematurely satisfy the map barrier;
    # SURVEY.md §5 directs counting unique log transitions only.
    c = mk(files=2, n_reduce=1)
    c.request_task({}); c.request_task({})
    c.map_complete({"TaskNumber": 0})
    c.map_complete({"TaskNumber": 0})  # duplicate from a re-queued twin
    assert c.c_map == 1
    assert c.request_task({})["TaskStatus"] == TaskStatus.WAITING  # barrier holds


def test_wire_reply_fields_match_reference_shape():
    # WorkerReply fields (mr/rpc.go:22-33) are the wire contract.
    c = mk()
    r = c.request_task({})
    assert set(r) == {"TaskStatus", "NMap", "CMap", "NReduce", "CReduce", "Filename"}


def test_large_job_assignment_order_and_requeue():
    """Scheduler scalability redesign (heap + single watchdog thread): the
    reference's lowest-index-first assignment order must survive at 10k
    tasks, and requeued tasks must re-enter in index order."""
    import time

    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator

    n = 10_000
    # Long timeout for the bulk-assignment phase: a loaded machine must not
    # let the watchdog requeue mid-loop and break the order assertion.
    c = Coordinator([f"f{i}" for i in range(n)], 4,
                    JobConfig(n_reduce=4, task_timeout_s=600.0))
    try:
        t0 = time.perf_counter()
        for i in range(n):
            assert c.request_task({})["CMap"] == i
        assert time.perf_counter() - t0 < 10.0  # O(n log n), not O(n^2)
    finally:
        c.close()

    # Requeue order on a small job with a short timeout: tasks 7 and 3
    # complete; everything else times out and must be reassigned
    # lowest-index-first, skipping the completed ones.
    c = Coordinator([f"f{i}" for i in range(10)], 4,
                    JobConfig(n_reduce=4, task_timeout_s=0.3))
    try:
        for i in range(10):
            assert c.request_task({})["CMap"] == i
        c.map_complete({"TaskNumber": 7})
        c.map_complete({"TaskNumber": 3})
        deadline = time.time() + 10.0
        reassigned = []
        while len(reassigned) < 3 and time.time() < deadline:
            r = c.request_task({})
            if r["TaskStatus"] == 0:
                reassigned.append(r["CMap"])
            else:
                time.sleep(0.05)
        assert reassigned == [0, 1, 2]
    finally:
        c.close()
