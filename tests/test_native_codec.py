"""Native C++ KV decoder: differential against the Python json path.

The invariant under test: for every input, the native decoder either
produces exactly what the Python decoder produces, or declines (None) so
the Python decoder runs — native and pure runs can never diverge.
"""

import json
import os

import pytest

from dsi_tpu import native
from dsi_tpu.mr.types import KeyValue
from dsi_tpu.mr.worker import read_intermediates, write_intermediates

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def python_decode(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            out.append((obj["Key"], obj["Value"]))
    return out


def write_records(path, records):
    with open(path, "w") as f:
        for k, v in records:
            f.write(json.dumps({"Key": k, "Value": v}) + "\n")


TRICKY = [
    ("plain", "1"),
    ('quote"inside', "back\\slash"),
    ("tab\there", "new\nline"),
    ("unicode: héllo wörld", "emoji: \U0001F600"),  # surrogate pair as \uXXXX
    ("control \x01\x1f", "\b\f\r"),
    ("", ""),
    ("ключ", "значение"),
]


def test_roundtrip_tricky_strings(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    write_records(path, TRICKY)
    got = native.decode_kv_file(path)
    assert got == python_decode(path) == TRICKY


def test_large_file_equivalence(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    records = [(f"word{i % 997}", str(i)) for i in range(20000)]
    write_records(path, records)
    assert native.decode_kv_file(path) == python_decode(path)


def test_torn_tail_defers_to_python(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    write_records(path, [("a", "1"), ("b", "2")])
    with open(path, "a") as f:
        f.write('{"Key": "c", "Val')  # crashed writer
    # strict parser can't prove completeness -> defers
    assert native.decode_kv_file(path) is None
    assert python_decode(path) == [("a", "1"), ("b", "2")]


def test_missing_file_defers(tmp_path):
    assert native.decode_kv_file(os.path.join(str(tmp_path), "nope")) is None


def test_blank_lines_tolerated(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    with open(path, "w") as f:
        f.write('\n{"Key": "a", "Value": "1"}\n\n   \n'
                '{"Key": "b", "Value": "2"}\n')
    assert native.decode_kv_file(path) == [("a", "1"), ("b", "2")]


def test_read_intermediates_native_vs_python(tmp_path):
    wd = str(tmp_path)
    kva = [KeyValue(k, v) for k, v in TRICKY] * 50
    write_intermediates(kva, map_task=0, n_reduce=3, workdir=wd)
    write_intermediates(kva, map_task=1, n_reduce=3, workdir=wd)
    for r in range(3):
        os.environ["DSI_NO_NATIVE"] = "1"
        try:
            native._lib = None  # reset the load cache
            py = read_intermediates(r, 2, wd)
        finally:
            del os.environ["DSI_NO_NATIVE"]
        native._lib = None
        nat = read_intermediates(r, 2, wd)
        assert nat == py
        assert sum(len(read_intermediates(q, 2, wd)) for q in range(3)) \
            == len(kva) * 2


def test_lone_surrogate_defers(tmp_path):
    """json.dumps emits \\ud800 for lone surrogates; strict UTF-8 can't
    represent them — native must defer, not crash the reduce path."""
    path = os.path.join(str(tmp_path), "kv")
    with open(path, "w") as f:
        f.write(json.dumps({"Key": "bad\ud800", "Value": "1"}) + "\n")
    assert native.decode_kv_file(path) is None


def test_raw_control_char_matches_python_strictness(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    with open(path, "w") as f:
        f.write('{"Key": "ok", "Value": "1"}\n')
        f.write('{"Key": "bad\tchar", "Value": "2"}\n')  # raw tab: invalid
        f.write('{"Key": "after", "Value": "3"}\n')
    assert native.decode_kv_file(path) is None  # strict stop -> defer
    assert python_decode(path) == [("ok", "1")]  # python breaks there too


# ---- the native map-side encoder (kv_encode_partitions) ----

def python_write_intermediates(kva, map_task, n_reduce, workdir):
    """The REAL Python fallback of write_intermediates, forced by disabling
    the native library for the duration of one call."""
    prev = native._lib
    native._lib = False
    try:
        write_intermediates(kva, map_task, n_reduce, workdir)
    finally:
        native._lib = prev


def _decoded_partitions(workdir, map_task, n_reduce):
    out = []
    for r in range(n_reduce):
        p = os.path.join(workdir, f"mr-{map_task}-{r}")
        out.append(python_decode(p))
    return out


def test_encoder_matches_python_writer_partitions_and_records(tmp_path):
    kva = [KeyValue(k, v) for k, v in TRICKY * 3] + [
        KeyValue(f"word{i}", str(i)) for i in range(500)]
    nat = tmp_path / "native"
    py = tmp_path / "python"
    nat.mkdir(), py.mkdir()
    write_intermediates(kva, 0, 7, str(nat))       # native path (available)
    python_write_intermediates(kva, 0, 7, str(py))
    assert _decoded_partitions(str(nat), 0, 7) == \
        _decoded_partitions(str(py), 0, 7)


def test_encoder_blobs_decode_natively_and_with_json(tmp_path):
    kva = [KeyValue(k, v) for k, v in TRICKY]
    blobs = native.encode_partitions(kva, 3)
    assert blobs is not None
    seen = []
    for r, blob in enumerate(blobs):
        p = tmp_path / f"mr-9-{r}"
        p.write_bytes(blob)
        nat = native.decode_kv_file(str(p))
        pyd = python_decode(str(p))
        assert nat is None or nat == pyd
        seen.extend(pyd)
    # Every record lands in exactly one partition, values intact.
    assert sorted(seen) == sorted(TRICKY)


def test_encoder_partitioner_is_reference_ihash(tmp_path):
    from dsi_tpu.mr.worker import ihash

    kva = [KeyValue(f"k{i}", "") for i in range(200)]
    blobs = native.encode_partitions(kva, 10)
    for r, blob in enumerate(blobs):
        p = tmp_path / f"b{r}"
        p.write_bytes(blob)
        for k, _ in python_decode(str(p)):
            assert ihash(k) % 10 == r


def test_encoder_surrogate_defers():
    # A surrogate (undecodable to strict UTF-8) must route to the Python
    # writer rather than crash or mangle.
    kva = [KeyValue("bad\ud800key", "v")]
    assert native.encode_partitions(kva, 3) is None


def test_write_intermediates_native_off_equivalence(tmp_path, monkeypatch):
    kva = [KeyValue(f"w{i % 37}", str(i)) for i in range(300)]
    on = tmp_path / "on"
    off = tmp_path / "off"
    on.mkdir(), off.mkdir()
    write_intermediates(kva, 2, 5, str(on))
    monkeypatch.setattr(native, "_lib", False)  # force pure-Python path
    write_intermediates(kva, 2, 5, str(off))
    monkeypatch.setattr(native, "_lib", None)
    assert _decoded_partitions(str(on), 2, 5) == \
        _decoded_partitions(str(off), 2, 5)


# ── native wc job bodies (round 5: wcjob.cpp) ──────────────────────────


def test_native_wc_map_matches_combiner(tmp_path):
    import json

    from dsi_tpu import native
    from dsi_tpu.apps.tpu_wc import Map
    from dsi_tpu.mr.worker import ihash

    if not native.available():
        pytest.skip("no native toolchain")
    raw = (b"the quick the lazy dog12dog cat-cat foo_bar " * 500
           + b"tail without newline")
    p = tmp_path / "split.txt"
    p.write_bytes(raw)
    blobs = native.wc_map_file(str(p), 10)
    assert blobs is not None
    got = {}
    for r, blob in enumerate(blobs):
        for line in blob.decode().splitlines():
            o = json.loads(line)
            assert ihash(o["Key"]) % 10 == r
            got[o["Key"]] = got.get(o["Key"], 0) + int(o["Value"])
    want = {kv.key: int(kv.value) for kv in Map("f", raw.decode())}
    assert got == want


def test_native_wc_map_declines_non_ascii(tmp_path):
    from dsi_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "u.txt"
    p.write_bytes("the café".encode())
    assert native.wc_map_file(str(p), 10) is None


def test_native_wc_reduce_matches_python(tmp_path):
    """Native reduce over a MIX of native- and Python-written
    intermediates must equal the host group/sort/reduce output."""
    import io
    import json

    from dsi_tpu import native
    from dsi_tpu.apps.tpu_wc import Reduce
    from dsi_tpu.mr.worker import group_and_reduce, read_intermediates
    from dsi_tpu.mr.types import KeyValue

    if not native.available():
        pytest.skip("no native toolchain")
    wd = str(tmp_path)
    # map 0: native-format blob; map 1: Python json.dumps writer; map 2
    # missing (tolerated).
    (tmp_path / "mr-0-3").write_bytes(
        b'{"Key": "apple", "Value": "2"}\n{"Key": "zebra", "Value": "5"}\n')
    with open(tmp_path / "mr-1-3", "w") as f:
        for k, v in (("apple", "3"), ("mango", "1")):
            f.write(json.dumps({"Key": k, "Value": v}) + "\n")
    blob = native.wc_reduce(wd, 3, 3)
    assert blob is not None
    buf = io.StringIO()
    group_and_reduce(read_intermediates(3, 3, wd), Reduce, buf)
    assert blob.decode() == buf.getvalue()
    assert blob.decode() == "apple 5\nmango 1\nzebra 5\n"


def test_native_wc_reduce_declines_escapes_and_bad_values(tmp_path):
    from dsi_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    (tmp_path / "mr-0-1").write_bytes(
        b'{"Key": "caf\\u00e9", "Value": "1"}\n')
    assert native.wc_reduce(str(tmp_path), 1, 1) is None
    (tmp_path / "mr-0-2").write_bytes(b'{"Key": "a", "Value": "x1"}\n')
    assert native.wc_reduce(str(tmp_path), 2, 1) is None


def test_native_runner_unicode_split_falls_back_exact(tmp_path):
    """End-to-end through the runner seam: a unicode split routes its map
    to the host combiner (escaped JSON), and the reduce then declines to
    Python — final output still exact."""
    import io

    from dsi_tpu.apps import tpu_wc
    from dsi_tpu.backends.native import NativeTaskRunner
    from dsi_tpu.mr.worker import group_and_reduce, read_intermediates

    r = NativeTaskRunner(tpu_wc)
    split = tmp_path / "s.txt"
    split.write_text("the café the naïve dog café")
    r.run_map(tpu_wc.Map, str(split), 0, 4, str(tmp_path))
    for part in range(4):
        r.run_reduce(tpu_wc.Reduce, part, 1, str(tmp_path))
    out = "".join(open(tmp_path / f"mr-out-{p}").read() for p in range(4))
    rows = dict(line.rsplit(" ", 1) for line in out.splitlines())
    assert rows == {"the": "2", "café": "2", "naïve": "1", "dog": "1"}


def test_native_wc_reduce_declines_concatenated_records(tmp_path):
    """Two records on one line: the Python decoder breaks there
    (reference semantics) — native must defer, not parse both."""
    from dsi_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    (tmp_path / "mr-0-4").write_bytes(
        b'{"Key": "a", "Value": "1"}{"Key": "b", "Value": "2"}\n')
    assert native.wc_reduce(str(tmp_path), 4, 1) is None


def test_native_wc_reduce_declines_u64_overflow(tmp_path):
    from dsi_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    big = '{"Key": "a", "Value": "999999999999999999"}\n' * 21
    (tmp_path / "mr-0-5").write_bytes(big.encode())
    assert native.wc_reduce(str(tmp_path), 5, 1) is None


def test_native_indexer_bodies_match_host(tmp_path):
    """Native indexer map+reduce vs the host app path, mixed encoders."""
    import io

    from dsi_tpu import native
    from dsi_tpu.apps.indexer import Map, Reduce
    from dsi_tpu.mr.worker import (group_and_reduce, ihash,
                                   read_intermediates, write_intermediates)

    if not native.available():
        pytest.skip("no native toolchain")
    d0 = tmp_path / "docA.txt"
    d0.write_bytes(b"red fish blue fish and red dog12dog")
    d1 = tmp_path / "docB.txt"
    d1.write_bytes(b"blue whale and the dog")
    # map 0 native, map 1 via the host writer.
    blobs = native.idx_map_file(str(d0), str(d0), 6)
    assert blobs is not None
    for r, blob in enumerate(blobs):
        (tmp_path / f"mr-0-{r}").write_bytes(blob)
    write_intermediates(Map(str(d1), d1.read_bytes().decode()), 1, 6,
                        str(tmp_path))
    for r in range(6):
        blob = native.idx_reduce(str(tmp_path), r, 2)
        assert blob is not None
        buf = io.StringIO()
        group_and_reduce(read_intermediates(r, 2, str(tmp_path)), Reduce,
                         buf)
        assert blob.decode() == buf.getvalue(), r
    # Spot-check content: 'blue' appears in both docs.
    r = ihash("blue") % 6
    blob = native.idx_reduce(str(tmp_path), r, 2).decode()
    assert f"blue 2 {d0},{d1}\n" in blob


def test_native_indexer_declines_unescapable_docname(tmp_path):
    from dsi_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "doc.txt"
    p.write_bytes(b"plain words")
    assert native.idx_map_file(str(p), 'doc"quote', 4) is None
    assert native.idx_map_file(str(p), "café", 4) is None


def test_native_grep_bodies_match_host(tmp_path, monkeypatch):
    """Native literal-grep map+reduce vs the host re path end-to-end,
    including lines needing the minimal escape set."""
    import io

    from dsi_tpu import native
    from dsi_tpu.apps.grep import Map, Reduce
    from dsi_tpu.mr.worker import (group_and_reduce, read_intermediates,
                                   write_intermediates)

    if not native.available():
        pytest.skip("no native toolchain")
    monkeypatch.setenv("DSI_GREP_PATTERN", "dog")
    raw = (b'the "dog" barked\tloudly\n'
           b"no match here\n"
           b"dog and dog again\n"
           b"back\\slash dog line\n"
           b"the dog\n"
           b"the dog\n"
           b"tail dog without newline")
    p = tmp_path / "s.txt"
    p.write_bytes(raw)
    blobs = native.grep_map_file(str(p), "dog", 4)
    assert blobs is not None
    for r, blob in enumerate(blobs):
        (tmp_path / f"mr-0-{r}").write_bytes(blob)
    # A second map task via the Python writer (mixed encoders).
    write_intermediates(Map(str(p), raw.decode()), 1, 4, str(tmp_path))
    for r in range(4):
        blob = native.grep_reduce(str(tmp_path), r, 2)
        assert blob is not None, r
        buf = io.StringIO()
        group_and_reduce(read_intermediates(r, 2, str(tmp_path)), Reduce,
                         buf)
        assert blob.decode() == buf.getvalue(), r


def test_native_grep_declines_regex_and_unicode(tmp_path):
    from dsi_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "s.txt"
    p.write_bytes(b"plain dog line\n")
    assert native.grep_map_file(str(p), "do+g", 4) is None  # regex: host re
    assert native.grep_map_file(str(p), "café", 4) is None
    p2 = tmp_path / "u.txt"
    p2.write_bytes("the café dog\n".encode())
    assert native.grep_map_file(str(p2), "dog", 4) is None  # unicode split


def test_native_tfidf_map_matches_host(tmp_path):
    import json

    from dsi_tpu import native
    from dsi_tpu.apps.tfidf import Map
    from dsi_tpu.mr.worker import ihash

    if not native.available():
        pytest.skip("no native toolchain")
    raw = b"red fish blue fish red red dog12dog"
    p = tmp_path / "docA.txt"
    p.write_bytes(raw)
    blobs = native.tfidf_map_file(str(p), str(p), 6)
    assert blobs is not None
    got = {}
    for r, blob in enumerate(blobs):
        for line in blob.decode().splitlines():
            o = json.loads(line)
            assert ihash(o["Key"]) % 6 == r
            got[o["Key"]] = o["Value"]
    want = {kv.key: kv.value for kv in Map(str(p), raw.decode())}
    assert got == want
