"""Native C++ KV decoder: differential against the Python json path.

The invariant under test: for every input, the native decoder either
produces exactly what the Python decoder produces, or declines (None) so
the Python decoder runs — native and pure runs can never diverge.
"""

import json
import os

import pytest

from dsi_tpu import native
from dsi_tpu.mr.types import KeyValue
from dsi_tpu.mr.worker import read_intermediates, write_intermediates

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def python_decode(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            out.append((obj["Key"], obj["Value"]))
    return out


def write_records(path, records):
    with open(path, "w") as f:
        for k, v in records:
            f.write(json.dumps({"Key": k, "Value": v}) + "\n")


TRICKY = [
    ("plain", "1"),
    ('quote"inside', "back\\slash"),
    ("tab\there", "new\nline"),
    ("unicode: héllo wörld", "emoji: \U0001F600"),  # surrogate pair as \uXXXX
    ("control \x01\x1f", "\b\f\r"),
    ("", ""),
    ("ключ", "значение"),
]


def test_roundtrip_tricky_strings(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    write_records(path, TRICKY)
    got = native.decode_kv_file(path)
    assert got == python_decode(path) == TRICKY


def test_large_file_equivalence(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    records = [(f"word{i % 997}", str(i)) for i in range(20000)]
    write_records(path, records)
    assert native.decode_kv_file(path) == python_decode(path)


def test_torn_tail_defers_to_python(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    write_records(path, [("a", "1"), ("b", "2")])
    with open(path, "a") as f:
        f.write('{"Key": "c", "Val')  # crashed writer
    # strict parser can't prove completeness -> defers
    assert native.decode_kv_file(path) is None
    assert python_decode(path) == [("a", "1"), ("b", "2")]


def test_missing_file_defers(tmp_path):
    assert native.decode_kv_file(os.path.join(str(tmp_path), "nope")) is None


def test_blank_lines_tolerated(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    with open(path, "w") as f:
        f.write('\n{"Key": "a", "Value": "1"}\n\n   \n'
                '{"Key": "b", "Value": "2"}\n')
    assert native.decode_kv_file(path) == [("a", "1"), ("b", "2")]


def test_read_intermediates_native_vs_python(tmp_path):
    wd = str(tmp_path)
    kva = [KeyValue(k, v) for k, v in TRICKY] * 50
    write_intermediates(kva, map_task=0, n_reduce=3, workdir=wd)
    write_intermediates(kva, map_task=1, n_reduce=3, workdir=wd)
    for r in range(3):
        os.environ["DSI_NO_NATIVE"] = "1"
        try:
            native._lib = None  # reset the load cache
            py = read_intermediates(r, 2, wd)
        finally:
            del os.environ["DSI_NO_NATIVE"]
        native._lib = None
        nat = read_intermediates(r, 2, wd)
        assert nat == py
        assert sum(len(read_intermediates(q, 2, wd)) for q in range(3)) \
            == len(kva) * 2


def test_lone_surrogate_defers(tmp_path):
    """json.dumps emits \\ud800 for lone surrogates; strict UTF-8 can't
    represent them — native must defer, not crash the reduce path."""
    path = os.path.join(str(tmp_path), "kv")
    with open(path, "w") as f:
        f.write(json.dumps({"Key": "bad\ud800", "Value": "1"}) + "\n")
    assert native.decode_kv_file(path) is None


def test_raw_control_char_matches_python_strictness(tmp_path):
    path = os.path.join(str(tmp_path), "kv")
    with open(path, "w") as f:
        f.write('{"Key": "ok", "Value": "1"}\n')
        f.write('{"Key": "bad\tchar", "Value": "2"}\n')  # raw tab: invalid
        f.write('{"Key": "after", "Value": "3"}\n')
    assert native.decode_kv_file(path) is None  # strict stop -> defer
    assert python_decode(path) == [("ok", "1")]  # python breaks there too
