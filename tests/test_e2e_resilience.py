"""End-to-end resilience: coordinator crash/resume, and the TCP control
plane — real processes, full wire path.

VERDICT r3 tasks 6 and 7: journal resume was unit-tested only
(tests/test_journal.py) and TCP+HMAC was exercised only at the RPC layer
(tests/test_rpc.py).  These tests close both gaps at the process level:

* SIGKILL the coordinator mid-job, restart it with the same ``--journal``,
  and require completion with oracle parity — the capability the reference
  lacks entirely (its coordinator state is process-local,
  ``mr/coordinator.go:17,21``; death loses the job).
* Run the whole job over ``DSI_MR_SOCKET=tcp:127.0.0.1:0`` with a shared
  ``DSI_MR_SECRET``: the coordinator announces its kernel-assigned port,
  workers join over authenticated TCP — the reference's intended
  multi-host variant (``mr/coordinator.go:124``, ``mr/worker.go:173``).
"""

import json
import os
import re
import subprocess
import sys
import time

import pytest

from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args, cwd, env, **kw):
    kw.setdefault("stdout", subprocess.DEVNULL)
    kw.setdefault("stderr", subprocess.DEVNULL)
    return subprocess.Popen([sys.executable, "-m", *args], cwd=cwd, env=env,
                            **kw)


def _base_env(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSI_MR_SOCKET"] = str(tmp_path / "mr.sock")
    return env


def _journaled_maps(jpath: str) -> int:
    """Completed-map records currently in the journal (0 if absent)."""
    if not os.path.exists(jpath):
        return 0
    n = 0
    with open(jpath, "rb") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                break
            if isinstance(rec, dict) and rec.get("kind") == "map":
                n += 1
    return n


@pytest.mark.slow
def test_coordinator_crash_resume_e2e(tmp_path):
    """SIGKILL the coordinator after >=1 journaled map completion but
    before the job ends; a restarted coordinator on the same journal plus
    fresh workers must finish with oracle parity."""
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=12,
                          file_size=60_000)
    wd = str(tmp_path)
    want = oracle_output("wc", files, wd)
    env = _base_env(tmp_path)
    jpath = str(tmp_path / "journal")
    coord_args = ["dsi_tpu.cli.mrcoordinator", "--journal", jpath,
                  "--task-timeout", "2.0", *files]

    coord = _spawn(coord_args, wd, env)
    workers = []
    try:
        time.sleep(0.5)  # socket-creation grace (test-mr.sh:39-40)
        workers = [_spawn(["dsi_tpu.cli.mrworker", "wc"], wd, env)
                   for _ in range(2)]
        deadline = time.time() + 60
        while _journaled_maps(jpath) < 1:
            if time.time() > deadline:
                pytest.fail("no map completion journaled in 60s")
            if coord.poll() is not None:
                pytest.fail("job finished before the crash could be "
                            "injected; enlarge the corpus")
            time.sleep(0.02)
        coord.kill()  # SIGKILL mid-job: no cleanup, journal is all that survives
        coord.wait(timeout=10)
        assert _journaled_maps(jpath) < len(files), \
            "crash landed after all maps finished; enlarge the corpus"
        # Orphaned workers exit on their own once the socket is gone
        # (worker.go:173 semantics: unreachable coordinator = job over).
        for w in workers:
            w.wait(timeout=30)

        coord = _spawn(coord_args, wd, env)
        time.sleep(0.5)
        workers = [_spawn(["dsi_tpu.cli.mrworker", "wc"], wd, env)
                   for _ in range(2)]
        assert coord.wait(timeout=90) == 0
        for w in workers:
            w.wait(timeout=30)
    finally:
        for p in (coord, *workers):
            if p.poll() is None:
                p.kill()
    assert merged_output(wd) == want
    assert len(want) > 1000


@pytest.mark.slow
def test_tcp_control_plane_e2e(tmp_path):
    """Full job over authenticated TCP: coordinator on tcp:127.0.0.1:0
    announces its kernel-assigned address; 3 workers join over it."""
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=5,
                          file_size=50_000)
    wd = str(tmp_path)
    want = oracle_output("wc", files, wd)
    env = _base_env(tmp_path)
    env["DSI_MR_SOCKET"] = "tcp:127.0.0.1:0"
    env["DSI_MR_SECRET"] = "e2e-shared-secret"

    errpath = tmp_path / "coord.err"
    with open(errpath, "w") as errf:
        coord = _spawn(["dsi_tpu.cli.mrcoordinator", *files], wd, env,
                       stderr=errf)
    workers = []
    try:
        addr = None
        deadline = time.time() + 30
        while addr is None:
            if time.time() > deadline:
                pytest.fail("coordinator never announced its TCP address")
            m = re.search(r"listening on (tcp:\S+)",
                          errpath.read_text(errors="replace"))
            if m:
                addr = m.group(1)
            else:
                time.sleep(0.05)
        wenv = dict(env)
        wenv["DSI_MR_SOCKET"] = addr
        workers = [_spawn(["dsi_tpu.cli.mrworker", "wc"], wd, wenv)
                   for _ in range(3)]
        assert coord.wait(timeout=90) == 0
        for w in workers:
            w.wait(timeout=30)
    finally:
        for p in (coord, *workers):
            if p.poll() is None:
                p.kill()
    assert merged_output(wd) == want
