"""Class-pattern device grep (ops/regexk.py) vs the host ``re`` oracle.

Differential discipline as everywhere else: for every supported pattern,
the kernel's matching lines must equal a per-line ``re.search`` scan (the
host app's exact semantics, apps/grep.py:34); unsupported patterns must
return None so the host path decides.
"""

import random
import re

import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.ops.regexk import classgrep_host_result, parse_class_pattern


def _oracle(data: bytes, pattern: str):
    pat = re.compile(pattern)
    return [l for l in data.decode("ascii").split("\n") if pat.search(l)]


SUPPORTED = [
    "[Tt]he",                 # the reference harness's own pattern
    "gr[ae]y",
    "w.rd",
    r"\d\d",
    r"[a-f]x[0-9A-F]",
    "[^aeiou ]ight",
    r"^The",
    r"ed$",
    r"^[A-Z].....$",
    r"\.txt",
    r"\w\s\w",
    r"[a\]b]",                # escaped ']' inside a class
    r"[\d;]x",
]

TEXT = (
    "The quick brown fox\n"
    "bracket ] here; 7x marks\n"
    "a gray day, a grey sky\n"
    "word w0rd weird ward\n"
    "42 is the answer; 0xAF too\n"
    "light fight might sight eight aight\n"
    "Theodore spoke\n"
    "they walked and talked\n"
    "file.txt and fileAtxt\n"
    "SHOUTY\n"
    "ends with ed\n"
    "no trailing newline"
).encode()


@pytest.mark.parametrize("pattern", SUPPORTED)
def test_supported_patterns_match_re_oracle(pattern):
    got = classgrep_host_result(TEXT, pattern)
    assert got is not None, f"{pattern!r} unexpectedly unsupported"
    assert got == _oracle(TEXT, pattern), pattern


@pytest.mark.parametrize("pattern", [
    "a*b", "a+?", "x{2,3}", "(ab)", "a|b", r"\bword", "", "[]", "[z-a]x",
    "a^b", "café",
])
def test_unsupported_patterns_route_to_host(pattern):
    assert parse_class_pattern(pattern) is None
    assert classgrep_host_result(TEXT, pattern) is None


def test_nul_bytes_route_to_host():
    assert classgrep_host_result(b"a\x00b\nxy\n", "[ab]") is None


def test_whitespace_class_covers_ascii_control_separators():
    # re's \s (str mode) matches \x1c-\x1f; these bytes pass the ascii
    # gate, so the kernel's class table must include them.
    data = b"a\x1cb\nc d\nef\n"
    assert classgrep_host_result(data, r"\w\s\w") == _oracle(data, r"\w\s\w")


def test_fuzz_class_patterns_vs_oracle():
    rng = random.Random(13)
    alphabet = "abcDE12 .,"
    for trial in range(25):
        lines = ["".join(rng.choices(alphabet, k=rng.randint(0, 30)))
                 for _ in range(rng.randint(1, 40))]
        data = "\n".join(lines).encode()
        pattern = rng.choice(SUPPORTED + ["[abc]", r"\d", "..", "[^a]b"])
        got = classgrep_host_result(data, pattern)
        assert got is not None
        assert got == _oracle(data, pattern), (trial, pattern, lines)


def test_fuzz_generated_class_patterns_vs_oracle():
    """Random patterns BUILT from the supported grammar (not a fixed
    list): every generated pattern must be accepted and agree with the
    per-line re.search oracle."""
    rng = random.Random(29)
    alphabet = "abcxyzAB01 .,;"

    def gen_atom():
        r = rng.random()
        if r < 0.3:
            return rng.choice("abcxyzAB"), None
        if r < 0.45:
            return ".", None
        if r < 0.6:
            return rng.choice([r"\d", r"\w", r"\s"]), None
        neg = "^" if rng.random() < 0.3 else ""
        items = []
        for _ in range(rng.randint(1, 3)):
            if rng.random() < 0.5:
                lo, hi = sorted(rng.sample("abcdwxyz", 2))
                items.append(f"{lo}-{hi}")
            else:
                items.append(rng.choice("abcxyz019"))
        return f"[{neg}{''.join(items)}]", None

    for trial in range(40):
        pattern = "".join(gen_atom()[0]
                          for _ in range(rng.randint(1, 5)))
        if rng.random() < 0.2:
            pattern = "^" + pattern
        if rng.random() < 0.2:
            pattern = pattern + "$"
        lines = ["".join(rng.choices(alphabet, k=rng.randint(0, 24)))
                 for _ in range(rng.randint(1, 30))]
        data = "\n".join(lines).encode()
        got = classgrep_host_result(data, pattern)
        assert got is not None, (trial, pattern)
        assert got == _oracle(data, pattern), (trial, pattern, lines)


def test_line_buffer_overflow_retries_exactly():
    # every byte a newline: n_lines = n+1 forces the widest l_cap rung
    data = b"\n" * 600 + b"xa\n" * 40
    got = classgrep_host_result(data, "[xy]a")
    assert got == _oracle(data, "[xy]a")


def test_anchors_respect_line_boundaries():
    data = b"abc\nxabc\nabcx\nabc"
    assert classgrep_host_result(data, "^abc") == _oracle(data, "^abc")
    assert classgrep_host_result(data, "abc$") == _oracle(data, "abc$")
    assert classgrep_host_result(data, "^abc$") == _oracle(data, "^abc$")
