"""The one-command job runner (cli/mrrun.py): real child processes,
oracle-checked — the scripted form of the reference's manual
coordinator+workers choreography (main/test-mr.sh:36-45)."""

import os
import subprocess
import sys

from dsi_tpu.utils.corpus import ensure_corpus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=180, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "dsi_tpu.cli.mrrun"] + args,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_mrrun_wc_parity(tmp_path):
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=3,
                          file_size=30_000)
    wd = tmp_path / "job"
    p = _run(["--workers", "2", "--workdir", str(wd), "--check", "wc"]
             + files)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr
    outs = [f for f in os.listdir(wd) if f.startswith("mr-out-")]
    assert len(outs) == 10


def test_mrrun_crash_app_respawns_and_finishes(tmp_path):
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=3,
                          file_size=8_000)
    wd = tmp_path / "job"
    p = _run(["--workers", "2", "--task-timeout", "2.0",
              "--workdir", str(wd), "--check", "crash"] + files,
             env_extra={"DSI_CRASH_EXIT_PROB": "0.3"})
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr


def test_mrrun_bad_app_fails_fast_without_respawn_storm(tmp_path):
    import time

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2,
                          file_size=4_000)
    wd = tmp_path / "job"
    t0 = time.monotonic()
    p = _run(["--workers", "2", "--workdir", str(wd), "--timeout", "120",
              "no_such_app"] + files)
    elapsed = time.monotonic() - t0
    assert p.returncode != 0
    assert "failing repeatedly" in p.stderr
    # The instant-death streak detector (same exit code, zero tasks
    # completed) must abort after a handful of respawn rounds — seconds
    # of interpreter startups, not the old ~26-respawn budget that ran
    # the clock toward the 90 s wall (VERDICT r5 weak #5).
    assert "consecutive instant deaths" in p.stderr
    assert elapsed < 45


def test_mrrun_journal_resume_keeps_committed_outputs(tmp_path):
    # Resume semantics: with an existing journal, committed mr-out-* files
    # ARE the checkpoint — the resumed coordinator marks journaled tasks
    # done and never regenerates them, so mrrun must NOT sweep them (the
    # no-journal sweep is tested by test_mrrun_reports_coordinator_failure).
    # Re-execution of the *unjournaled* remainder is covered at the
    # coordinator level by tests/test_journal.py.
    from dsi_tpu.mr.journal import Journal

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2,
                          file_size=10_000)
    wd = tmp_path / "job"
    wd.mkdir()
    jpath = str(wd / "ckpt.journal")

    # A complete run provides the committed outputs of the "crashed" job.
    p = _run(["--workers", "2", "--workdir", str(wd), "--check", "wc"]
             + files)
    assert p.returncode == 0
    committed = {r: (wd / f"mr-out-{r}").read_text() for r in range(10)}

    j = Journal(jpath, [os.path.abspath(f) for f in files], 10)
    j.open()
    for m in range(len(files)):
        j.record("map", m)
    for r in range(10):
        j.record("reduce", r)
    j.close()

    p = _run(["--workers", "2", "--workdir", str(wd),
              "--journal", jpath, "--check", "wc"] + files)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr
    for r in range(10):
        assert (wd / f"mr-out-{r}").read_text() == committed[r]


def test_mrrun_tpu_backend_parity(tmp_path):
    # --backend tpu plumbing end-to-end (kernels pinned to host CPU, the
    # same route scripts/test_mr.sh tpu_wc tpu exercises).
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2,
                          file_size=20_000)
    wd = tmp_path / "job"
    p = _run(["--workers", "2", "--workdir", str(wd), "--backend", "tpu",
              "--check", "tpu_wc"] + files,
             env_extra={"DSI_JAX_PLATFORM": "cpu"})
    assert p.returncode == 0, p.stderr[-2000:]
    assert "parity OK" in p.stderr


def test_mrrun_reports_coordinator_failure(tmp_path):
    # A coordinator that cannot start (unauthenticated non-loopback TCP is
    # refused, mr/rpc.py) must surface as a non-zero mrrun exit — never a
    # silent success (and never a stale-output parity pass).
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2,
                          file_size=4_000)
    wd = tmp_path / "job"
    wd.mkdir()
    (wd / "mr-out-0").write_text("stale 1\n")  # must not survive the run
    p = _run(["--workers", "1", "--workdir", str(wd), "--check", "wc"]
             + files,
             env_extra={"DSI_MR_SOCKET": "tcp:0.0.0.0:0"})
    assert p.returncode != 0
    assert "coordinator exited" in p.stderr
    assert not (wd / "mr-out-0").exists()
