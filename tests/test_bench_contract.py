"""bench.py verdict-contract tests.

The driver records bench.py's single stdout JSON line as the round's
BENCH artifact; every failure mode must still produce one (the
always-emit-a-verdict discipline of the reference harness,
test-mr.sh:55-59).  These tests drive the real script in a subprocess
with a small corpus and assert the verdict shapes:

* accelerator half disabled (deadline < 60 s) -> error verdict with a
  port diagnosis, rc=1, and NO cpu fallback (stays fast);
* accelerator attempts failing (zero-second timeouts) -> the CPU-fallback
  verdict under its own metric name with tpu_error attached, rc=0.

Under pytest the child runs on the virtual-CPU platform (conftest env),
which stands in for the chip; the contract under test is the verdict
plumbing, not device performance.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def run_bench(tmp_path, extra_env, timeout=420):
    env = dict(os.environ)
    env.update({
        "DSI_BENCH_FILES": "2",
        "DSI_BENCH_FILE_SIZE": "200000",
        "DSI_BENCH_REPS": "1",
        "DSI_BENCH_FRAMEWORK_MB": "2",  # default 48 MB would dominate
        "DSI_BENCH_TFIDF_MB": "2",      # engine rows at contract-test
        "DSI_BENCH_GREP_MB": "2",       # scale: the verdict plumbing is
                                        # under test, not throughput
        "DSI_BENCH_MESH_MB": "1",       # mesh A/B row: two 8-vdev
                                        # subprocess passes ride every
                                        # verdict — keep them short here
        # Isolated workdir + compile cache: must NOT touch the repo's
        # canonical .bench corpus/oracle (the warm loop's parity checks
        # read them) or write CPU-platform entries into the persistent
        # .jaxcache reserved for chip runs.
        "DSI_BENCH_WORKDIR": str(tmp_path / "bench-wd"),
        "JAX_COMPILATION_CACHE_DIR": str(tmp_path / "jaxcache"),
        "DSI_AOT_CACHE_DIR": str(tmp_path / "aotcache"),
    })
    env.update(extra_env)
    p = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout, env=env)
    lines = [l for l in p.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"want exactly one JSON line, got {p.stdout!r}"
    return p.returncode, json.loads(lines[0])


@pytest.mark.slow
def test_disabled_accelerator_half_emits_error_verdict(tmp_path):
    rc, v = run_bench(tmp_path, {"DSI_BENCH_DEADLINE_S": "30"})
    assert rc == 1
    assert v["metric"] == "wc_tpu_throughput"
    assert v["value"] == 0 and v["vs_baseline"] == 0
    assert v["oracle_mbps"] > 0      # the oracle half always measures
    assert "error" in v
    assert v["diagnosis"].count(":") >= 3   # three port probes reported


@pytest.mark.slow
def test_failed_attempts_fall_back_to_labeled_cpu_verdict(tmp_path):
    rc, v = run_bench(tmp_path, {"DSI_BENCH_TPU_TIMEOUTS": "0",
                                 "DSI_BENCH_DEADLINE_S": "600",
                                 "DSI_BENCH_STREAM_MB": "2",
                                 # serve row at contract-test scale:
                                 # 2 tenants x ~0.2 MB keeps the daemon
                                 # + 2 one-shot CLI boots inside the
                                 # test budget while exercising the
                                 # measured path
                                 "DSI_BENCH_SERVE_JOBS": "2",
                                 "DSI_BENCH_SERVE_MB": "0.2",
                                 # serve latency row at contract-test
                                 # scale: 4 grep tenants x 4 KB keeps
                                 # the two extra daemon boots short
                                 # while exercising both arms
                                 "DSI_BENCH_SERVE_LAT_TENANTS": "4",
                                 "DSI_BENCH_SERVE_LAT_KB": "4",
                                 # plan row at contract-test scale:
                                 # 2 planrun subprocesses (chained +
                                 # staged) over a 1 MB corpus
                                 "DSI_BENCH_PLAN_MB": "1",
                                 # net row at contract-test scale: two
                                 # mrrun fleets per pass — worker boots,
                                 # not MBs, dominate (hence the timeout
                                 # headroom over run_bench's 420)
                                 "DSI_BENCH_NET_MB": "1",
                                 # replica row at contract-test scale:
                                 # three shardrun fleets (one single,
                                 # two 3-replica groups incl. a leader
                                 # kill) — election walls, not MBs,
                                 # dominate
                                 "DSI_BENCH_REPLICA_MB": "0.5"},
                      timeout=600)
    assert rc == 0
    assert v["metric"] == "wc_cpu_fallback_throughput"
    assert v["platform"] == "cpu"
    assert v["value"] > 0
    assert "tpu_error" in v and "diagnosis" in v
    # vs_baseline is computed from the UNROUNDED oracle rate; recomputing
    # from the published (rounded) values differs by up to the relative
    # rounding error scaled by the ratio — and at small ratios the
    # 2-decimal rounding half-step (0.005) alone exceeds 2% relative, so
    # the abs term must cover it or the gate flakes with box speed.
    assert v["vs_baseline"] == pytest.approx(
        v["value"] / v["oracle_mbps"], rel=0.02, abs=0.006)
    # Honesty extras ride the same verdict line: the median, and either a
    # measured streaming row (with its own parity gate) or an explicit
    # skip reason — a silently-absent row is a contract violation.
    assert v["median_mbps"] > 0
    assert ("stream_skipped" in v) != ("stream_mbps" in v)
    if "stream_mbps" in v:
        assert v["stream_parity"] is True
        assert v["stream_mb"] >= 2
        # The checkpoint/restore cost keys ride the measured stream row
        # under the same measured-XOR-skipped contract (dsi_tpu/ckpt):
        # either both cost numbers with their parity gate, or a reason.
        # ISSUE 8 made the row a cadence-1 sync-vs-async A/B: the async
        # overhead + full-bytes keys always accompany the sync one, and
        # the per-delta bytes key rides exactly when the async pass
        # produced at least one incremental save.
        assert ("ckpt_skipped" in v) != ("ckpt_overhead_pct" in v)
        if "ckpt_overhead_pct" in v:
            assert v["resume_parity"] is True
            assert v["ckpt_saves"] >= 1
            assert v["resume_gap_s"] >= 0
            assert isinstance(v["ckpt_overhead_pct"], (int, float))
            assert isinstance(v["ckpt_async_overhead_pct"], (int, float))
            assert v["ckpt_every"] == 1
            assert v["ckpt_full_bytes_per_save"] > 0
            assert v["ckpt_barrier_s"] >= 0
            assert (("ckpt_delta_bytes_per_save" in v)
                    == (v["ckpt_deltas"] >= 1))
            if "ckpt_delta_bytes_per_save" in v:
                assert v["ckpt_delta_bytes_per_save"] > 0
                # Compressed deltas (ISSUE 13, default on): the raw
                # denominator and ratio ride alongside, and zlib on
                # the packed word tables must actually shrink them.
                if "ckpt_compress_ratio" in v:
                    assert v["ckpt_delta_raw_bytes_per_save"] > 0
                    assert v["ckpt_compress_ratio"] > 1.0
    # The distributed N-worker row (the reference's own headline shape,
    # test-mr.sh:36-53) rides the same verdict: measured or skipped.
    assert ("framework_skipped" in v) != ("framework_mbps" in v)
    if "framework_mbps" in v:
        assert v["framework_parity"] is True
        assert v["framework_workers"] >= 3
        assert v["framework_vs_oracle"] == pytest.approx(
            v["framework_mbps"] / v["framework_oracle_mbps"],
            rel=0.02, abs=0.006)  # abs covers the 2-decimal rounding step
    # The engine rows honor the same measured-XOR-skipped contract.
    assert ("tfidf_skipped" in v) != ("tfidf_mbps" in v)
    assert ("grep_skipped" in v) != ("grep_mbps" in v)
    if "grep_mbps" in v:
        assert v["grep_parity"] is True
        assert v["grep_oracle_mbps"] > 0
    # The mesh-vs-host-merge A/B row (ISSUE 7): measured XOR skipped,
    # and a measured row carries the parity gate, the per-sync pull
    # bytes BOTH ways, and the per-shard widen counters.
    assert ("mesh_skipped" in v) != ("mesh_shuffle_mbps" in v)
    if "mesh_shuffle_mbps" in v:
        assert v["mesh_parity"] is True
        assert v["mesh_shards"] >= 2
        assert v["mesh_pull_bytes_per_sync"] > 0
        assert v["mesh_host_pull_bytes_per_sync"] > 0
        assert len(v["mesh_shard_widens"]) == v["mesh_shards"]
    # The compressed-wire + parallel-ingest A/B row (ISSUE 13):
    # measured XOR skipped, with the ingest keys as the completion
    # marker (a mid-row parity failure ships its skip reason plus
    # whatever halves had already measured cleanly).
    assert ("wire_skipped" in v) != ("readahead_hit_pct" in v)
    if "readahead_hit_pct" in v:
        assert v["wire_parity"] is True
        assert v["wire_ratio"] > 1.0   # dictionary+varint vs raw rows
        assert v["wire_upload_parity"] is True
        assert v["ingest_parity"] is True
        assert v["ingest_readers"] == 4
        assert v["ingest_materialize_s"] >= 0
        assert v["ingest_serial_materialize_s"] >= 0
    # The serving-daemon A/B row (ISSUE 11): measured XOR skipped; a
    # measured row carries the per-tenant parity gate, both throughput
    # halves, and the amortized warm cost.
    assert ("serve_skipped" in v) != ("serve_packed_mbps" in v)
    if "serve_packed_mbps" in v:
        assert v["serve_parity"] is True
        assert v["serve_jobs"] >= 2
        assert v["serve_oneshot_mbps"] > 0
        assert v["serve_amortized_warm_s"] >= 0
    # The serving-QoS packed-grep latency A/B row (ISSUE 19): measured
    # XOR skipped; a measured row carries the per-tenant byte-parity
    # gate, BOTH arms' p50/p99, and the packing evidence.
    assert ("serve_lat_skipped" in v) != ("serve_pack_p99_s" in v)
    if "serve_pack_p99_s" in v:
        assert v["serve_lat_parity"] is True
        assert v["serve_lat_tenants"] >= 2
        assert v["serve_pack_p50_s"] >= 0
        assert v["serve_pack_p99_s"] >= v["serve_pack_p50_s"]
        assert v["serve_tmux_p99_s"] >= v["serve_tmux_p50_s"] >= 0
        assert v["serve_lat_packed_steps"] >= 1
    # The plan-layer chained-vs-staged A/B row (ISSUE 14): measured XOR
    # skipped; a measured row carries the byte-parity gate, BOTH
    # throughputs, and the zero-host-bytes invariant of the
    # device-resident handoff against the staged materialization.
    assert ("plan_skipped" in v) != ("plan_chained_mbps" in v)
    if "plan_chained_mbps" in v:
        assert v["plan_parity"] is True
        assert v["plan_zero_copy"] is True
        assert v["plan_intermediate_bytes"] == 0
        assert v["plan_staged_intermediate_bytes"] > 0
        assert v["plan_staged_mbps"] > 0
        # The elastic pipelined arm (ISSUE 16) rides the measured plan
        # row: same chain run with stage overlap, parity-gated against
        # the same staged oracle, plus the attributed overlap wall.
        assert v["plan_pipelined_mbps"] > 0
        assert v["plan_overlap_s"] >= 0
    # The speculative-execution A/B row (ISSUE 15): measured XOR
    # skipped; a measured row carries both arms' throughput, the
    # backup-fired evidence, and the zero-duplicate-commit invariant
    # (first-commit-wins), each arm parity-gated in its subprocess.
    assert ("spec_skipped" in v) != ("spec_backup_mbps" in v)
    if "spec_backup_mbps" in v:
        assert v["spec_parity"] is True
        assert v["spec_nobackup_mbps"] > 0
        assert v["spec_backup_fired"] >= 1
        assert v["spec_duplicate_commits"] == 0
        assert v["spec_exactly_once"] is True
        # The dynamic re-split arm (ISSUE 16) rides the measured spec
        # row under its own measured-XOR-skipped gate (the trigger is
        # load-dependent; a no-fire run skips honestly).  A measured
        # arm carries the dispatch evidence, and its duplicate commits
        # are already folded into spec_duplicate_commits above.
        assert ("spec_resplit_skipped" in v) != ("spec_resplit_mbps"
                                                 in v)
        if "spec_resplit_mbps" in v:
            assert v["spec_resplits"] >= 1
            assert v["spec_subshards"] >= 2
    # The network-data-plane A/B row (ISSUE 17): measured XOR skipped;
    # a measured row carries both planes' throughput, the codec's wire
    # leverage (the >= 1.5 acceptance bar), and the locality evidence,
    # each arm parity-gated in its subprocess.
    assert ("net_skipped" in v) != ("net_shuffle_mbps" in v)
    if "net_shuffle_mbps" in v:
        assert v["net_parity"] is True
        assert v["net_fs_mbps"] > 0
        assert v["net_fetches"] + v["net_local_reads"] > 0
        assert v["net_ratio"] >= 1.5
        assert v["locality_hits"] >= 0
        assert v["net_refetches"] == 0  # no chaos in the bench arm
    # The overlapped-shuffle pipelined-vs-serial fetch A/B row
    # (ISSUE 18): measured XOR skipped; a measured row carries both
    # arms' fetch throughput under the SAME injected serve latency,
    # byte parity between them, and the overlap attribution (dialer
    # wire time hidden behind the consumer — the >= 1.2x acceptance
    # bar rides the throughput pair).
    assert ("net_pipeline_skipped" in v) != ("net_pipelined_mbps" in v)
    if "net_pipelined_mbps" in v:
        assert v["net_pipeline_parity"] is True
        assert v["net_serial_mbps"] > 0
        assert v["net_pipe_mb"] > 0
        assert v["net_overlap_s"] >= 0
        assert v["net_fetch_wait_s"] >= 0
    # The replicated-control-plane A/B row (ISSUE 20): measured XOR
    # skipped; a measured row carries all three arms' throughput
    # (single coordinator, 3-replica group, group with the leader
    # kill -9'd), the majority-commit overhead, the failover wall with
    # its term handoff, and the exactly-once-across-terms bool (stats
    # plus every replica journal audited inside the row).
    assert ("replica_skipped" in v) != ("replica_failover_s" in v)
    if "replica_failover_s" in v:
        assert v["replica_parity"] is True
        assert v["replica_single_mbps"] > 0
        assert v["replica_group_mbps"] > 0
        assert v["replica_chaos_mbps"] > 0
        assert v["replica_failover_s"] > 0
        assert v["replica_terms"][1] > v["replica_terms"][0] >= 1
        assert v["replica_duplicate_commits"] == 0
        assert v["replica_exactly_once"] is True


def test_engine_phase_dicts_come_from_the_registry(tmp_path):
    """Schema contract (dsi_tpu/obs/registry.py): every engine's phase
    dict IS a registered MetricsScope, and its unified view carries the
    one documented key set — killing the stream/wave/grep key drift.
    The alias table is closed: a legacy spelling surviving into the
    unified view, or a brand-new drift key, fails here."""
    jax = pytest.importorskip("jax")
    from dsi_tpu.obs.registry import (LEGACY_ALIASES, SCHEMA_KEYS,
                                      MetricsScope, get_registry)
    from dsi_tpu.parallel.grepstream import (grep_streaming,
                                             indexer_streaming)
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import wordcount_streaming
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    mesh = default_mesh(8)
    text = ("alpha beta gamma delta the fox " * 400).encode()
    assert wordcount_streaming([text], mesh=mesh, n_reduce=4,
                               chunk_bytes=1 << 11,
                               u_cap=1 << 9) is not None
    assert grep_streaming([b"the fox\nno match here\nthe the\n" * 100],
                          "the", mesh=mesh,
                          chunk_bytes=1 << 11) is not None
    docs = [b"alpha beta alpha", b"beta gamma", b"delta the fox"]
    assert tfidf_sharded(docs, mesh=mesh, n_reduce=4,
                         u_cap=1 << 8) is not None
    assert indexer_streaming(docs, mesh=mesh, n_reduce=4,
                             u_cap=1 << 8) is not None

    reg = get_registry()
    for engine in ("stream", "grep", "tfidf", "indexer"):
        sc = reg.phases(engine)
        assert isinstance(sc, MetricsScope), \
            f"{engine} phase dict is not a registry scope"
        assert sc.engine == engine
        u = sc.unified()
        # The unified phase keys every engine must report.
        for key in ("materialize_s", "upload_s", "kernel_s", "pull_s",
                    "merge_s", "replay_s"):
            assert key in u, (engine, key)
        for key in ("depth", "replays", "step_pulls"):
            assert key in u, (engine, key)
        # No legacy spelling leaks through the unified view.
        assert not (set(LEGACY_ALIASES) & set(u)), (engine, u)
        # ONE source of truth (ISSUE 12): every unified key an engine
        # actually reports is in the registry's machine-readable
        # schema — the same tuple the dsicheck metric-schema rule
        # gates writes against, so this list and the static gate
        # cannot drift apart.
        drift = set(u) - set(SCHEMA_KEYS)
        assert not drift, (engine, sorted(drift))
    # The registry snapshot (embedded in trace artifacts) carries all
    # four engines under the same shape.
    snap = reg.snapshot()["engines"]
    assert {"stream", "grep", "tfidf", "indexer"} <= set(snap)


def test_mesh_shard_keys_reconcile_with_span_totals(tmp_path):
    """Schema contract for the mesh-sharded service keys (ISSUE 7):
    a mesh run's phase dict carries the documented counters
    (``mesh_shards``/``pull_bytes``/``shard_widens``/
    ``shard_imbalance``), fold spans land in the tracer's ``shuffle``
    lane, and the span totals reconcile with ``fold_s`` — the span IS
    the stats accumulator, so the two cannot drift."""
    pytest.importorskip("jax")
    from dsi_tpu.obs import get_tracer
    from dsi_tpu.obs.registry import get_registry
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import wordcount_streaming

    mesh = default_mesh(8)
    tr = get_tracer()
    was_enabled = tr.enabled
    tr.enabled = True
    mark = tr.mark()
    try:
        text = ("alpha beta gamma delta the fox jumps " * 600).encode()
        pstats: dict = {}
        assert wordcount_streaming(
            [text], mesh=mesh, n_reduce=10, chunk_bytes=1 << 11,
            u_cap=1 << 9, mesh_shards=8,
            pipeline_stats=pstats) is not None
        with tr._lock:
            evs = tr._events[mark:]
    finally:
        tr.enabled = was_enabled
    for key in ("mesh_shards", "pull_bytes", "shard_widens",
                "shard_imbalance", "folds", "fold_s"):
        assert key in pstats, key
    assert pstats["mesh_shards"] == 8
    assert pstats["pull_bytes"] > 0
    assert len(pstats["shard_widens"]) == 8
    # The registry scope mirrors the same dict.
    sc = get_registry().phases("stream")
    assert sc is not None and sc.get("mesh_shards") == 8
    # Fold spans in the shuffle lane, totals == fold_s (same clock).
    fold_spans = [e for e in evs if e[0] == "X" and e[1] == "fold"]
    assert fold_spans and all(e[2] == "shuffle" for e in fold_spans)
    assert sum(e[4] for e in fold_spans) == pytest.approx(
        pstats["fold_s"], rel=0.05, abs=0.05)


def test_schema_is_single_sourced():
    """The registry's SCHEMA_KEYS is THE schema: it contains every
    phase key and every alias target, has no duplicates, and the
    dsicheck metric-schema rule reads the very same tuple — so adding
    an engine key is exactly one edit in obs/registry.py."""
    from dsi_tpu.analysis.rules import schema as schema_rule
    from dsi_tpu.obs.registry import (COUNTER_KEYS, LEGACY_ALIASES,
                                      PHASE_KEYS, SCHEMA_KEYS)

    assert set(PHASE_KEYS) <= set(SCHEMA_KEYS)
    assert set(COUNTER_KEYS) <= set(SCHEMA_KEYS)
    assert len(SCHEMA_KEYS) == len(set(SCHEMA_KEYS)), "duplicate keys"
    # every legacy spelling maps INTO the schema, never out of it
    assert set(LEGACY_ALIASES.values()) <= set(SCHEMA_KEYS)
    # the static gate accepts exactly schema + legacy spellings
    assert schema_rule._ALLOWED == \
        frozenset(SCHEMA_KEYS) | frozenset(LEGACY_ALIASES)


def test_histogram_keys_pinned_in_registry_schema():
    """Schema contract for the live telemetry plane (ISSUE 10): the
    hot-stage set and the per-stage snapshot keys are PINNED — every
    consumer (/statusz, /metrics, trace meta, tracecat's percentile
    table, bench rollups) keys on them, so changing either is a schema
    change and must fail here first."""
    from dsi_tpu.obs import hist
    from dsi_tpu.obs.registry import get_registry

    assert hist.HIST_STAGES == ("kernel", "upload", "pull", "finish",
                                "fold", "sync", "ckpt_commit")
    assert hist.HIST_SNAPSHOT_KEYS == ("count", "total_s", "p50_ms",
                                       "p90_ms", "p99_ms", "max_ms")
    hist.deactivate(force=True)
    try:
        # Off: the snapshot carries no histograms key at all.
        assert "histograms" not in get_registry().snapshot()
        hs = hist.activate()
        hs.record("kernel", 0.004)
        hs.record("not_a_stage", 1.0)  # non-hot names drop silently
        snap = get_registry().snapshot()
        assert set(snap["histograms"]) == {"kernel"}
        assert tuple(snap["histograms"]["kernel"]) == \
            hist.HIST_SNAPSHOT_KEYS
    finally:
        hist.deactivate(force=True)


@pytest.mark.slow
def test_stream_row_disabled_leaves_no_stream_keys(tmp_path):
    rc, v = run_bench(tmp_path, {"DSI_BENCH_TPU_TIMEOUTS": "0",
                                 "DSI_BENCH_DEADLINE_S": "600",
                                 "DSI_BENCH_STREAM_MB": "0",
                                 "DSI_BENCH_FRAMEWORK_MB": "0",
                                 "DSI_BENCH_NET_MB": "1"})
    assert rc == 0
    assert not any(k.startswith("stream_") for k in v)
    assert not any(k.startswith("framework_") for k in v)
    # No stream row -> no checkpoint cost keys either (they ride it).
    assert not any(k.startswith(("ckpt_", "resume_")) for k in v)
