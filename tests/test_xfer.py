"""Upload-mode transfer helper (ops/xfer.py): both modes move the same
bytes, stats record the wall, and bad env values fall back to async."""

import numpy as np
import pytest

from dsi_tpu.ops import xfer


@pytest.fixture()
def views():
    rng = np.random.default_rng(7)
    return [rng.integers(0, 255, size=1 << 12, dtype=np.uint8)
            for _ in range(3)]


@pytest.mark.parametrize("mode", ["async", "sync"])
def test_put_views_roundtrip(views, mode, monkeypatch):
    monkeypatch.setenv("DSI_UPLOAD_MODE", mode)
    out = xfer.put_views(views)
    assert len(out) == len(views)
    for host, dev in zip(views, out):
        np.testing.assert_array_equal(host, np.asarray(dev))
    assert xfer.stats["upload_mode"] == mode
    assert xfer.stats["upload_s"] >= 0.0


def test_bad_mode_falls_back_to_async(views, monkeypatch):
    monkeypatch.setenv("DSI_UPLOAD_MODE", "banana")
    xfer.put_views(views)
    assert xfer.stats["upload_mode"] == "async"


def test_explicit_device(views, monkeypatch):
    import jax

    monkeypatch.setenv("DSI_UPLOAD_MODE", "sync")
    dev = jax.devices()[0]
    out = xfer.put_views(views, device=dev)
    assert all(list(d.devices()) == [dev] for d in out)
