"""Replica-group integration over the real RPC transport (jax-free).

tests/test_raft.py pins the deterministic core; these pin the process
harness around it — :class:`ReplicaNode` groups over unix sockets with
real threads, real timers, a trivial leader application:

* a 3-node group elects exactly one leader and serves app RPCs from it;
* followers answer app RPCs with the typed ``NotLeader{hint}``
  redirect, and ``group_call`` resolves it transparently;
* ``propose_and_wait`` replicates to every live node's applier in log
  order, exactly once;
* killing the leader elects a successor, the group keeps serving, and
  re-delivered committed entries do not duplicate in any applier;
* :class:`JournalApplier` dedups re-proposed journal records by
  content, so every replica's journal file replays to one record per
  task (the ``duplicate_commits == 0`` backbone);
* :class:`AdmissionApplier` materializes admitted jobs idempotently.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from dsi_tpu.mr import rpc
from dsi_tpu.mr.journal import Journal
from dsi_tpu.replica import client as rclient
from dsi_tpu.replica.node import (AdmissionApplier, JournalApplier,
                                  ReplicaNode)

# Tight timers: these tests wait on real elections.
ELECTION = (0.15, 0.35)
HEARTBEAT = 0.05


class EchoApp:
    """Minimal leader application: serves Echo, counts closes."""

    instances = 0

    def __init__(self):
        EchoApp.instances += 1
        self.closed = False

    def close(self):
        self.closed = True


def make_group(tmp_path, n=3):
    addrs = [str(tmp_path / f"r{i}.sock") for i in range(n)]
    logs = [[] for _ in range(n)]
    nodes = []
    for i in range(n):
        def applier(idx, data, _log=logs[i]):
            _log.append((idx, data))

        def factory():
            app = EchoApp()
            return app, {"App.Echo": lambda a: {"echo": a.get("x")}}

        nodes.append(ReplicaNode(
            i, addrs, str(tmp_path / f"r{i}.rlog"),
            applier=applier, app_factory=factory,
            app_methods=("App.Echo",),
            election_timeout_s=ELECTION, heartbeat_s=HEARTBEAT))
    for nd in nodes:
        nd.start()
    return nodes, logs, addrs


def wait_leader(nodes, alive=None, timeout=8.0):
    """The unique live leader, once a majority agrees on its term."""
    alive = set(range(len(nodes))) if alive is None else set(alive)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [nd for i, nd in enumerate(nodes)
                   if i in alive and nd.core.is_leader()]
        if len(leaders) == 1 and leaders[0].app() is not None:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no stable leader emerged")


def close_all(nodes):
    for nd in nodes:
        try:
            nd.close()
        except Exception:
            pass


def test_group_elects_serves_and_replicates(tmp_path):
    nodes, logs, addrs = make_group(tmp_path)
    try:
        leader = wait_leader(nodes)
        spec = ",".join(addrs)
        # App RPC through the group resolves to the leader (possibly
        # via redirects) and round-trips.
        ok, reply = rclient.group_call(spec, "App.Echo", {"x": 42},
                                       give_up_s=8.0)
        assert ok and reply == {"echo": 42}
        # Replication: proposals land in EVERY node's applier, in log
        # order, exactly once.
        idx1 = leader.propose_and_wait({"v": "a"})
        idx2 = leader.propose_and_wait({"v": "b"})
        assert idx2 == idx1 + 1
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(any(d == {"v": "b"} for _, d in log) for log in logs):
                break
            time.sleep(0.02)
        for log in logs:
            data = [d for _, d in log if isinstance(d, dict) and "v" in d]
            assert data == [{"v": "a"}, {"v": "b"}]
            idxs = [i for i, _ in log]
            assert idxs == sorted(idxs) and len(idxs) == len(set(idxs))
    finally:
        close_all(nodes)


def test_follower_redirects_to_leader(tmp_path):
    nodes, _, addrs = make_group(tmp_path)
    try:
        leader = wait_leader(nodes)
        follower = next(nd for nd in nodes if nd is not leader)
        ok, reply = rpc.call(follower.address, "App.Echo", {"x": 1})
        assert ok and reply["error_type"] == rclient.NOT_LEADER
        assert reply["hint"] == leader.address
    finally:
        close_all(nodes)


def test_leader_failover_serves_and_stays_exactly_once(tmp_path):
    nodes, logs, addrs = make_group(tmp_path)
    spec = ",".join(addrs)
    try:
        leader = wait_leader(nodes)
        first = leader.index
        leader.propose_and_wait({"v": "pre"})
        leader.close()  # the kill; rudely enough for this layer
        rclient.forget_leader(spec)
        survivors = [i for i in range(3) if i != first]
        t0 = time.monotonic()
        leader2 = wait_leader(nodes, alive=survivors)
        failover_s = time.monotonic() - t0
        assert leader2.index != first
        # The group serves again, through redirects alone.
        ok, reply = rclient.group_call(spec, "App.Echo", {"x": 7},
                                       give_up_s=10.0)
        assert ok and reply == {"echo": 7}
        leader2.propose_and_wait({"v": "post"})
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(any(d == {"v": "post"} for _, d in logs[i])
                   for i in survivors):
                break
            time.sleep(0.02)
        for i in survivors:
            data = [d for _, d in logs[i]
                    if isinstance(d, dict) and "v" in d]
            # Exactly once, in order, across the term change.
            assert data == [{"v": "pre"}, {"v": "post"}]
        # Not a wall-clock gate (CI noise), just evidence it measured.
        assert failover_s > 0.0
    finally:
        close_all(nodes)


def test_journal_applier_dedups_and_replays(tmp_path):
    files = [str(tmp_path / "in.txt")]
    path = str(tmp_path / "replica-0.journal")
    ja = JournalApplier(path, files, 0, n_shards=4)
    try:
        ja(1, {"kind": "raft_noop"})  # ignored
        ja(2, {"j": {"kind": "shard", "task": 1, "attempt": 3,
                     "crc": 99}})
        ja(3, {"j": {"kind": "shard", "task": 1, "attempt": 3,
                     "crc": 99}})  # duplicate: dropped
        ja(4, {"j": {"kind": "shard", "task": 2, "attempt": 1,
                     "crc": 7}})
        ja(5, {"j": {"kind": "resplit", "task": 3,
                     "ranges": [[0, 5], [5, 9]]}})
        ja(6, {"j": {"kind": "subshard", "task": 3, "sub": 0,
                     "attempt": 2, "crc": 1}})
        ja(7, {"j": {"kind": "subshard", "task": 3, "sub": 1,
                     "attempt": 4, "crc": 2}})
        ja(8, {"j": {"kind": "subshard", "task": 3, "sub": 1,
                     "attempt": 4, "crc": 2}})  # duplicate
    finally:
        ja.close()
    j = Journal(path, files, 0, n_shards=4)
    assert j.replay() == ([], [])
    assert j.shard_commits == {1: (3, 99), 2: (1, 7)}
    assert j.resplits == {3: [(0, 5), (5, 9)]}
    assert j.subshard_commits == {(3, 0): (2, 1), (3, 1): (4, 2)}
    # A fresh applier over the same file re-seeds its dedup set from
    # replay: the restart-redelivery path cannot double-append either.
    ja2 = JournalApplier(path, files, 0, n_shards=4)
    try:
        ja2(2, {"j": {"kind": "shard", "task": 1, "attempt": 3,
                      "crc": 99}})
    finally:
        ja2.close()
    j2 = Journal(path, files, 0, n_shards=4)
    j2.replay()
    assert j2.shard_commits == {1: (3, 99), 2: (1, 7)}


def test_admission_applier_idempotent(tmp_path):
    spool = str(tmp_path / "spool")
    aa = AdmissionApplier(spool)
    job = {"job_id": "t-000001", "tenant": "t", "app": "wc",
           "files": ["/x"], "state": "queued"}
    aa(1, {"admit": job})
    path = os.path.join(spool, "jobs", "t-000001.json")
    with open(path, encoding="utf-8") as f:
        assert json.load(f)["job_id"] == "t-000001"
    before = os.stat(path).st_mtime_ns
    aa(2, {"admit": job})  # re-delivery: no rewrite
    assert os.stat(path).st_mtime_ns == before
    aa(3, {"admit": {"no": "job_id"}})  # malformed: ignored
    assert [n for n in sorted(os.listdir(os.path.join(spool, "jobs")))
            if n.endswith(".json")] == ["t-000001.json"]


def test_group_call_single_address_passthrough(tmp_path):
    srv = rpc.RpcServer(str(tmp_path / "one.sock"),
                        {"Ping": lambda a: {"pong": True}})
    srv.start()
    try:
        ok, reply = rclient.group_call(srv.address, "Ping", {})
        assert ok and reply == {"pong": True}
    finally:
        srv.close()


def test_group_call_gives_up_on_dead_group(tmp_path):
    spec = ",".join(str(tmp_path / f"dead{i}.sock") for i in range(3))
    fake = {"t": 0.0}

    def clock():
        return fake["t"]

    def sleep(s):
        fake["t"] += s

    with pytest.raises(rpc.CoordinatorGone):
        rclient.group_call(spec, "Ping", {}, give_up_s=1.0,
                           sleep=sleep, clock=clock)
