"""Device grep kernel: differential vs the host regex app."""

import os

import pytest

pytest.importorskip("jax")

from dsi_tpu.apps import grep, tpu_grep
from dsi_tpu.ops.grepk import grep_host_result, is_literal_pattern

TEXT = (b"the quick brown fox\njumps over the lazy dog\n"
        b"no match here\nfoxes and boxes\n\nfox")


def host_lines(data: bytes, pattern: str):
    os.environ["DSI_GREP_PATTERN"] = pattern
    try:
        return [kv.key for kv in grep.Map("f", data.decode())]
    finally:
        del os.environ["DSI_GREP_PATTERN"]


def test_literal_detection():
    assert is_literal_pattern("fox")
    assert is_literal_pattern("lazy dog")
    assert not is_literal_pattern("[Tt]he")
    assert not is_literal_pattern("fox.*")
    assert not is_literal_pattern("")
    assert not is_literal_pattern("a\nb")
    assert not is_literal_pattern("héllo")


@pytest.mark.parametrize("pat", ["fox", "the", "dog", "zzz", "e", " "])
def test_kernel_matches_host_regex(pat):
    assert grep_host_result(TEXT, pat) == host_lines(TEXT, pat)


def test_empty_lines_and_final_line():
    out = grep_host_result(TEXT, "fox")
    assert out is not None
    assert out[-1] == "fox"  # final line without trailing newline


def test_line_buffer_overflow_retry():
    data = b"\n" * 3000 + b"needle\n" + b"\n" * 3000
    assert grep_host_result(data, "needle") == ["needle"]


def test_pattern_longer_than_data():
    assert grep_host_result(b"tiny", "a" * 300) == []


def test_regex_routing_tiers():
    # Class patterns leave the literal kernel (tier 1) but are now served
    # on device by the class kernel (tier 2, ops/regexk.py)...
    assert grep_host_result(TEXT, "[Tt]he") is None
    os.environ["DSI_GREP_PATTERN"] = "[Tt]he"
    try:
        kva = tpu_grep.tpu_map("f", TEXT)
        assert kva is not None and all("he" in kv.key for kv in kva)
    finally:
        del os.environ["DSI_GREP_PATTERN"]
    # ...variable-length regex is now served by tier 4 (the NFA
    # matrix-scan kernel, ops/nfak.py; pinned past the dispatch cost
    # model, which routes to host wherever the kernel measures slower)...
    os.environ["DSI_GREP_PATTERN"] = "th+e"
    os.environ["DSI_NFA_DISPATCH"] = "device"
    try:
        kva = tpu_grep.tpu_map("f", TEXT)
        assert kva is not None
        assert [kv.key for kv in kva] == [
            "the quick brown fox", "jumps over the lazy dog"]
    finally:
        del os.environ["DSI_GREP_PATTERN"]
        del os.environ["DSI_NFA_DISPATCH"]
    # ...while groups/backrefs still route to the host app.
    os.environ["DSI_GREP_PATTERN"] = "(th)+e"
    try:
        assert tpu_grep.tpu_map("f", TEXT) is None  # router: host handles it
    finally:
        del os.environ["DSI_GREP_PATTERN"]


def test_tpu_map_emits_per_line_records():
    os.environ["DSI_GREP_PATTERN"] = "fox"
    try:
        kva = tpu_grep.tpu_map("f", TEXT)
    finally:
        del os.environ["DSI_GREP_PATTERN"]
    assert [kv.key for kv in kva] == ["the quick brown fox",
                                      "foxes and boxes", "fox"]
    assert all(kv.value == "" for kv in kva)


def test_line_count_mismatch_falls_back(monkeypatch):
    # A host/device line-count disagreement must return None (host regex
    # path), not crash the worker task mid-job (VERDICT r2 weakness #5).
    import dsi_tpu.ops.grepk as grepk

    import dsi_tpu.ops.regexk as regexk

    real = grepk._grep_jit

    def skewed(chunk, pat, *, l_cap):
        line_match, n_lines, overflow = real(chunk, pat, l_cap=l_cap)
        return line_match, n_lines + 1, overflow

    monkeypatch.setattr(grepk, "_grep_jit", skewed)
    assert grep_host_result(TEXT, "fox") is None

    # A literal is also a valid class pattern, so tier 2 (regexk) would
    # otherwise serve the task; skew its line counts the same way to
    # assert the FULL device->host fallback chain.
    real_c = regexk._classgrep_compiled

    def skewed_c(n, ranges, a_start, a_end, l_cap):
        fn = real_c(n, ranges, a_start, a_end, l_cap)

        def wrap(chunk):
            line_match, n_lines, overflow = fn(chunk)
            return line_match, n_lines + 1, overflow

        return wrap

    monkeypatch.setattr(regexk, "_classgrep_compiled", skewed_c)
    assert regexk.classgrep_host_result(TEXT, "fox") is None

    # A literal is ALSO a valid tier-4 NFA pattern; skew its line counts
    # too so the router truly has no healthy device tier left.
    import dsi_tpu.ops.nfak as nfak

    real_n = nfak._nfa_compiled

    def skewed_n(n, s_bucket, block, l_cap):
        fn = real_n(n, s_bucket, block, l_cap)

        def wrap(chunk, table, v0):
            line_match, n_lines, overflow = fn(chunk, table, v0)
            return line_match, n_lines + 1, overflow

        return wrap

    monkeypatch.setattr(nfak, "_nfa_compiled", skewed_n)
    assert nfak.nfagrep_host_result(TEXT, "fox") is None

    # ...and the app-level router then serves the task via the host Map.
    monkeypatch.setenv("DSI_GREP_PATTERN", "fox")
    assert tpu_grep.tpu_map("f", TEXT) is None  # worker falls back to Map
    assert [kv.key for kv in grep.Map("f", TEXT.decode())] == [
        "the quick brown fox", "foxes and boxes", "fox"]


def test_control_byte_pattern_rejected():
    # NUL would match the chunk's zero padding; control bytes must route to
    # the host regex path
    assert not is_literal_pattern("\x00")
    assert not is_literal_pattern("a\x01b")
    assert grep_host_result(b"abc\x00x\ndef", "\x00") is None


def test_rung_gate_covers_all_tiers(monkeypatch):
    """Round-5 review: every grep tier must refuse a rung whose compiled
    shape is not persisted on an accelerator (host fallback), including
    the n+1 overflow escalation."""
    import dsi_tpu.ops.altk as altk
    import dsi_tpu.ops.grepk as grepk
    import dsi_tpu.ops.regexk as regexk

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(grepk.jax, "devices", lambda: [_FakeDev()])
    monkeypatch.setattr("dsi_tpu.backends.aotcache.is_persisted",
                        lambda *a, **k: False)
    data = b"the quick fox\nplain line\n" * 8
    assert grepk.grep_host_result(data, "fox") is None
    assert regexk.classgrep_host_result(data, "[Tt]he") is None
    assert altk.altgrep_host_result(data, "fox|[Tt]he") is None
    # Warm-script bypass keeps compiles possible where they are the job.
    monkeypatch.setenv("DSI_GREP_COLD_OK", "1")
    assert grepk.grep_host_result(data, "fox") is not None
