"""Speculative execution (ISSUE 15): shard plans, the scheduler's
backup/first-commit-wins protocol, chaos injection, dial backoff, and
the differential chaos harness.

Layers, cheapest first:

* pure-geometry units — newline-aligned shard plans, byte-exact stream
  slices, the merge/oracle codecs, chain adoption;
* scheduler units — the coordinator's shard handlers driven directly
  (no RPC server, no jax): assignment, setup grace, both backup
  triggers, presumed-dead requeue with resume hints, first-commit-wins
  arbitration, journal replay;
* the PR-9 detection half on its own (satellite): straggler_suspects
  ranking and dead/slow-task classification under synthetic heartbeat
  histories;
* satellites — jittered dial-backoff schedule + give-up bound, chaos
  knob determinism + a REAL ``os._exit`` subprocess;
* the differential chaos harness (slow) — a real ``shardrun`` fleet
  with a forced straggler AND a real mid-shard worker kill: backup
  fires, exactly one commit per shard, the killed shard resumes from
  its checkpoint (cursor > 0), output byte-identical to the
  sequential oracle.
"""

from __future__ import annotations

import errno
import json
import os
import subprocess
import sys
import time

import pytest

from dsi_tpu.config import JobConfig
from dsi_tpu.mr import rpc
from dsi_tpu.mr import shards as sh
from dsi_tpu.mr.coordinator import Coordinator
from dsi_tpu.mr.types import TaskStatus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_corpus(path, lines=400, words=12, vocab=37):
    rows = []
    for i in range(lines):
        rows.append(" ".join(
            "w" + chr(ord("a") + (i * words + j) % vocab) * 3
            for j in range(words)))
    data = ("\n".join(rows) + "\n").encode()
    with open(path, "wb") as f:
        f.write(data)
    return data


# ── shard geometry ─────────────────────────────────────────────────────


def test_plan_covers_stream_newline_aligned(tmp_path):
    p1 = str(tmp_path / "a.txt")
    p2 = str(tmp_path / "b.txt")
    write_corpus(p1, lines=100)
    write_corpus(p2, lines=57)
    files = [p1, p2]
    total = sh.stream_total_bytes(files)
    whole = b"".join(sh.read_stream_range(files, 0, total))
    assert len(whole) == total
    plan = sh.plan_shards(files, 5)
    assert plan[0].start == 0 and plan[-1].end == total
    for a, b in zip(plan, plan[1:]):
        assert a.end == b.start
        assert whole[b.start - 1:b.start] == b"\n"  # token/line safe cut
    # slices reassemble byte-exactly (separators included)
    got = b"".join(b"".join(sh.shard_blocks(files, spec, block_bytes=777))
                   for spec in plan)
    assert got == whole
    assert all(spec.size > 0 for spec in plan)


def test_read_stream_range_owns_trailing_separator(tmp_path):
    # Regression: a range ending exactly one byte past a file boundary
    # must include the inter-file separator byte — dropping it made the
    # slice one byte short and desynced grep's line counts at shard
    # edges that land on a separator.
    p1 = str(tmp_path / "a.txt")
    p2 = str(tmp_path / "b.txt")
    with open(p1, "wb") as f:
        f.write(b"hello\n")
    with open(p2, "wb") as f:
        f.write(b"world\n")
    files = [p1, p2]
    total = sh.stream_total_bytes(files)
    whole = b"".join(sh.read_stream_range(files, 0, total))
    assert whole == b"hello\n\nworld\n"
    # every split point reassembles exactly, incl. cut==7 (separator)
    for cut in range(total + 1):
        left = b"".join(sh.read_stream_range(files, 0, cut))
        right = b"".join(sh.read_stream_range(files, cut, total))
        assert left + right == whole, cut


def test_plan_merges_boundaries_inside_giant_line(tmp_path):
    p = str(tmp_path / "one.txt")
    with open(p, "wb") as f:
        f.write(b"x" * 5000 + b"\n" + b"tail line\n")
    plan = sh.plan_shards([p], 4)
    # every nominal cut inside the 5000-byte line collapses forward to
    # the single newline; no empty shard survives
    assert [s.size > 0 for s in plan] == [True] * len(plan)
    assert len(plan) <= 2


def test_wordcount_oracle_and_merge(tmp_path):
    p = str(tmp_path / "c.txt")
    data = write_corpus(p, lines=60)
    counts = sh.wordcount_host_oracle([data])
    import re

    naive = {}
    for w in re.findall(r"[A-Za-z]+", data.decode()):
        naive[w] = naive.get(w, 0) + 1
    assert counts == naive
    # shard-and-merge equals the whole-stream oracle
    plan = sh.plan_shards([p], 3)
    parts = []
    for spec in plan:
        c = sh.wordcount_host_oracle(sh.shard_blocks([p], spec))
        parts.append(sh.format_wordcount_counts(c))
    assert sh.merge_wordcount(parts) == sh.format_wordcount_counts(counts)


def test_adopt_chain_rules(tmp_path):
    src = str(tmp_path / "shard-0" / "a0")
    dst = str(tmp_path / "shard-0" / "a1")
    os.makedirs(src)
    for n in ("manifest-000001.json", "state-000001.npz",
              "state-000001.npz.crc32"):
        with open(os.path.join(src, n), "wb") as f:
            f.write(b"payload")
    sh.write_attempt_marker(src, 0, 0)
    # wrong shard refuses
    assert not sh.adopt_chain(src, dst, sid=7, attempt=1)
    assert sh.adopt_chain(src, dst, sid=0, attempt=1)
    assert sorted(os.listdir(dst)) == sorted(
        ["manifest-000001.json", "state-000001.npz",
         "state-000001.npz.crc32", sh.ATTEMPT_MARKER,
         sh.ATTEMPT_MARKER + ".crc32"])
    assert sh.read_attempt_marker(dst) == {"shard": 0, "attempt": 1}
    # a directory owned by another attempt refuses
    assert not sh.adopt_chain(src, dst, sid=0, attempt=2)
    # empty source refuses
    empty = str(tmp_path / "shard-0" / "a3")
    os.makedirs(empty)
    assert not sh.adopt_chain(empty, str(tmp_path / "shard-0" / "a4"),
                              sid=0, attempt=4)


def test_find_best_chain_picks_longest(tmp_path):
    root = str(tmp_path / "shard-2")
    for aid, seqs in ((0, (1, 2)), (1, (1, 2, 3)), (2, ())):
        d = os.path.join(root, f"a{aid}")
        os.makedirs(d)
        for s in seqs:
            with open(os.path.join(d, f"manifest-{s:06d}.json"),
                      "wb") as f:
                f.write(b"{}")
    assert sh.find_best_chain(root) == os.path.join(root, "a1")
    assert sh.find_best_chain(root, exclude_aid=1) == \
        os.path.join(root, "a0")


# ── scheduler units (handlers driven directly, no jax) ─────────────────


def mk_shard_coord(tmp_path, n_shards=2, journal=True, **cfg_kw):
    p = str(tmp_path / "in.txt")
    write_corpus(p, lines=200)
    plan = sh.plan_shards([p], n_shards)
    kw = dict(workdir=str(tmp_path), spec_floor_s=0.05,
              shard_timeout_s=5.0, spec_setup_s=8.0)
    kw.update(cfg_kw)
    if journal:
        kw["journal_path"] = str(tmp_path / "shards.journal")
    cfg = JobConfig(n_reduce=0, **kw)
    c = Coordinator([p], 0, cfg, shard_plan=plan,
                    shard_opts={"knobs": {"engine": "wordcount"}})
    return c, plan


def progress(c, r, confirmed=1, ckpts=0, cursor=0, wid=None):
    return c.shard_progress({"WorkerId": wid or "wX",
                             "Shard": r["Shard"], "Attempt": r["Attempt"],
                             "Confirmed": confirmed, "Ckpts": ckpts,
                             "ResumeCursor": cursor})


def commit(c, r, crc=1, payload=b"a 1\n", wid=None):
    with open(r["OutPart"], "wb") as f:
        f.write(payload)
    return c.commit_shard({"WorkerId": wid or "wX", "Shard": r["Shard"],
                           "Attempt": r["Attempt"], "Crc": crc})


def test_assigns_shards_then_waits(tmp_path):
    c, plan = mk_shard_coord(tmp_path)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        r1 = c.request_shard({"WorkerId": "w2"})
        assert {r0["TaskStatus"], r1["TaskStatus"]} == \
            {int(TaskStatus.SHARD)}
        assert {r0["Shard"], r1["Shard"]} == {0, 1}
        assert r0["End"] > r0["Start"] >= 0
        assert r0["ResumeFrom"] is None
        # both shards in flight, attempts fresh: setup grace holds any
        # speculation back even past the floor
        time.sleep(0.1)
        assert c.request_shard({"WorkerId": "w3"})["TaskStatus"] == \
            int(TaskStatus.WAITING)
    finally:
        c.close()


def test_backup_fires_on_progress_silence(tmp_path):
    c, plan = mk_shard_coord(tmp_path)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        r1 = c.request_shard({"WorkerId": "w2"})
        # both attempts past setup (real steps retired)…
        progress(c, r0, confirmed=3, ckpts=1, wid="w1")
        progress(c, r1, confirmed=3, wid="w2")
        # …then w1 goes silent past the floor while w2 keeps beating
        time.sleep(0.12)
        progress(c, r1, confirmed=4, wid="w2")
        rb = c.request_shard({"WorkerId": "w3"})
        assert rb["TaskStatus"] == int(TaskStatus.SHARD)
        assert rb["Shard"] == r0["Shard"]
        assert rb["ResumeFrom"] == r0["Attempt"]  # adopt w1's chain
        s = c.spec_stats()
        assert s["backup_dispatches"] == 1
        # a worker never backs itself up: w1 asking again gets WAITING
        # (its own shard is the only candidate)
        progress(c, r1, confirmed=5, wid="w2")
        assert c.request_shard({"WorkerId": "w2"})["TaskStatus"] == \
            int(TaskStatus.WAITING)
    finally:
        c.close()


def test_backup_fires_on_slow_progress(tmp_path):
    c, plan = mk_shard_coord(tmp_path, spec_floor_s=30.0, spec_k=2.0)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        r1 = c.request_shard({"WorkerId": "w2"})
        progress(c, r0, confirmed=1, wid="w1")
        assert commit(c, r1, wid="w2")["Win"]  # ref wall ~= 0
        time.sleep(0.1)
        progress(c, r0, confirmed=2, wid="w1")  # heartbeating, not silent
        rb = c.request_shard({"WorkerId": "w2"})
        assert rb["TaskStatus"] == int(TaskStatus.SHARD)
        assert rb["Shard"] == r0["Shard"]
    finally:
        c.close()


def test_first_commit_wins_loser_cancelled(tmp_path):
    c, plan = mk_shard_coord(tmp_path)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        r1 = c.request_shard({"WorkerId": "w2"})
        progress(c, r0, confirmed=3, ckpts=1, wid="w1")
        time.sleep(0.12)
        rb = c.request_shard({"WorkerId": "w3"})
        assert rb["Shard"] == r0["Shard"]
        # backup commits first -> wins; primary loses and is told so
        assert commit(c, rb, crc=42, wid="w3")["Win"]
        assert os.path.exists(os.path.join(
            str(tmp_path), f"mr-shard-out-{r0['Shard']}"))
        assert not commit(c, r0, crc=42, wid="w1")["Win"]
        assert progress(c, r0, wid="w1")["Cancel"]
        assert commit(c, r1, wid="w2")["Win"]
        assert c.done()
        s = c.spec_stats()
        assert s["commits"] == 2
        assert s["commit_losses"] == 1
        assert s["duplicate_commits"] == 0
        assert s["winning_attempts"][str(r0["Shard"])] == rb["Attempt"]
    finally:
        c.close()


def test_winner_recommit_counts_as_duplicate(tmp_path):
    # The invariant the harness gates on is MEASURABLE: a double commit
    # from the winning attempt increments duplicate_commits.
    c, plan = mk_shard_coord(tmp_path, n_shards=1)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        assert commit(c, r0, wid="w1")["Win"]
        assert not commit(c, r0, wid="w1")["Win"]
        assert c.spec_stats()["duplicate_commits"] == 1
    finally:
        c.close()


def test_dead_attempt_requeued_with_resume_hint(tmp_path):
    # speculation off: the watchdog's presumed-dead requeue must stand
    # on its own (a backup would otherwise cover the silence first)
    c, plan = mk_shard_coord(tmp_path, n_shards=1, shard_timeout_s=0.15,
                             spec_backup=False)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        progress(c, r0, confirmed=4, ckpts=2, wid="w1")
        deadline = time.monotonic() + 3.0
        r2 = None
        while time.monotonic() < deadline:
            time.sleep(0.05)
            r2 = c.request_shard({"WorkerId": "w2"})
            if r2["TaskStatus"] == int(TaskStatus.SHARD):
                break
        assert r2 is not None \
            and r2["TaskStatus"] == int(TaskStatus.SHARD)
        assert r2["Shard"] == r0["Shard"]
        assert r2["ResumeFrom"] == r0["Attempt"]
        s = c.spec_stats()
        assert s["requeues"] == 1
        # a resumed attempt reports its restore cursor
        progress(c, r2, confirmed=1, cursor=4096, wid="w2")
        s = c.spec_stats()
        assert s["resumed_attempts"] == 1
        assert s["resume_cursors"][
            f"{r2['Shard']}.a{r2['Attempt']}"] == 4096
    finally:
        c.close()


def test_shard_failed_requeues_and_exhaustion_fails_job(tmp_path):
    c, plan = mk_shard_coord(tmp_path, n_shards=1, shard_max_attempts=2)
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        c.shard_failed({"WorkerId": "w1", "Shard": r0["Shard"],
                        "Attempt": r0["Attempt"], "Reason": "hostpath"})
        r1 = c.request_shard({"WorkerId": "w2"})
        assert r1["TaskStatus"] == int(TaskStatus.SHARD)
        c.shard_failed({"WorkerId": "w2", "Shard": r1["Shard"],
                        "Attempt": r1["Attempt"], "Reason": "hostpath"})
        # budget spent: job fails instead of looping the poisoned shard
        assert c.request_shard({"WorkerId": "w3"})["TaskStatus"] == \
            int(TaskStatus.DONE)
        assert c.spec_stats()["job_failed"]
        assert c.done()
    finally:
        c.close()


def test_journal_replays_shard_commits(tmp_path):
    c, plan = mk_shard_coord(tmp_path)
    p = c.files[0]
    try:
        r0 = c.request_shard({"WorkerId": "w1"})
        assert commit(c, r0, crc=7, wid="w1")["Win"]
    finally:
        c.close()
    cfg = JobConfig(n_reduce=0, workdir=str(tmp_path),
                    journal_path=str(tmp_path / "shards.journal"))
    c2 = Coordinator([p], 0, cfg, shard_plan=plan, shard_opts={})
    try:
        s = c2.spec_stats()
        assert s["committed"] == 1
        assert s["winning_attempts"][str(r0["Shard"])] == r0["Attempt"]
        assert not c2.done()  # the other shard still needs running
        r = c2.request_shard({"WorkerId": "w9"})
        assert r["TaskStatus"] == int(TaskStatus.SHARD)
        assert r["Shard"] != r0["Shard"]
    finally:
        c2.close()


def test_journal_refuses_different_shard_plan(tmp_path):
    c, plan = mk_shard_coord(tmp_path, n_shards=2)
    p = c.files[0]
    c.close()
    cfg = JobConfig(n_reduce=0, workdir=str(tmp_path),
                    journal_path=str(tmp_path / "shards.journal"))
    with pytest.raises(SystemExit):
        Coordinator([p], 0, cfg,
                    shard_plan=sh.plan_shards([p], 3), shard_opts={})


# ── PR-9 detection half on its own (satellite) ─────────────────────────


def synth_worker(c, wid, gaps, silent_for):
    """Install a synthetic heartbeat history: ``gaps`` are the contact
    gaps (seconds) recorded into the worker's histogram; the worker's
    last contact is ``silent_for`` seconds ago."""
    from dsi_tpu.obs import LatencyHistogram

    h = LatencyHistogram()
    for g in gaps:
        h.record(g)
    with c.mu:
        c._hb_hist[wid] = h
        c._worker_seen[wid] = time.monotonic() - silent_for


def test_straggler_suspects_ranking(tmp_path):
    c, _ = mk_shard_coord(tmp_path, journal=False)
    try:
        # chatty worker gone quiet: p99 ~0.01, silent 30 s >> threshold
        synth_worker(c, "chatty", [0.01] * 50, silent_for=30.0)
        # slow-cadence worker: p99 ~20 s, silent 30 s < 2*p99=40 s
        synth_worker(c, "slowpoll", [20.0] * 50, silent_for=30.0)
        # healthy: silent 0.1 s
        synth_worker(c, "healthy", [0.01] * 50, silent_for=0.1)
        suspects = c.straggler_suspects(k=2.0)
        assert "chatty" in suspects
        assert "slowpoll" not in suspects
        assert "healthy" not in suspects
        assert suspects["chatty"] == pytest.approx(30.0, abs=1.0)
        # the threshold floor: with no gap history, task_timeout_s rules
        synth_worker(c, "nogaps", [], silent_for=30.0)
        assert "nogaps" in c.straggler_suspects(k=2.0)
    finally:
        c.close()


def test_presumed_classification(tmp_path):
    c, _ = mk_shard_coord(tmp_path, journal=False)
    try:
        now = time.monotonic()
        synth_worker(c, "deadish", [0.01] * 50, silent_for=5.0)
        synth_worker(c, "slowtask", [4.0] * 50, silent_for=5.0)
        with c.mu:
            age_d, p99_d, presumed_d = c._classify("deadish", now)
            age_s, p99_s, presumed_s = c._classify("slowtask", now)
            _, _, presumed_u = c._classify("neverseen", now)
        assert presumed_d == "dead" and age_d > 2 * p99_d
        assert presumed_s == "slow-task" and age_s <= 2 * p99_s
        assert presumed_u == "unknown"
    finally:
        c.close()


# ── dial backoff satellite ─────────────────────────────────────────────


def test_dial_backoff_schedule_pinned():
    # zero jitter draw: the exact doubling ladder
    lo = rpc.dial_backoff_schedule(rng=lambda: 0.0)
    assert lo == pytest.approx([0.05, 0.10, 0.20, 0.40, 0.80])
    # max jitter draw: every delay within (1 + _DIAL_JITTER)x, never less
    hi = rpc.dial_backoff_schedule(rng=lambda: 0.999999)
    for base, jit in zip(lo, hi):
        assert base <= jit <= base * (1.0 + rpc._DIAL_JITTER) + 1e-9
    # give-up bound: the whole retry budget stays under ~2.5 s
    assert sum(hi) < 2.5
    assert len(lo) == rpc._DIAL_ATTEMPTS - 1


def test_dial_gives_up_after_attempt_budget(monkeypatch):
    attempts = []

    class FakeSock:
        def settimeout(self, t):
            pass

        def connect(self, target):
            attempts.append(target)
            raise OSError(errno.ECONNREFUSED, "refused")

        def close(self):
            pass

    sleeps = []
    monkeypatch.setattr(rpc.socket, "socket",
                        lambda *a, **k: FakeSock())
    monkeypatch.setattr(rpc.time, "sleep", sleeps.append)
    with pytest.raises(rpc.CoordinatorGone):
        rpc._dial("unix", "/nonexistent/sock", "/nonexistent/sock", 1.0)
    assert len(attempts) == rpc._DIAL_ATTEMPTS
    assert len(sleeps) == rpc._DIAL_ATTEMPTS - 1
    for i, s in enumerate(sleeps):  # jittered exponential envelope
        base = rpc._DIAL_BACKOFF_S * (2 ** i)
        assert base <= s <= base * (1.0 + rpc._DIAL_JITTER) + 1e-9


def test_dial_nontransient_raises_immediately(monkeypatch):
    attempts = []

    class FakeSock:
        def settimeout(self, t):
            pass

        def connect(self, target):
            attempts.append(target)
            raise OSError(errno.ENOENT, "no such socket")

        def close(self):
            pass

    monkeypatch.setattr(rpc.socket, "socket",
                        lambda *a, **k: FakeSock())
    with pytest.raises(rpc.CoordinatorGone):
        rpc._dial("unix", "/gone", "/gone", 1.0)
    assert len(attempts) == 1


# ── chaos knob satellite ───────────────────────────────────────────────


def test_chaos_spec_parse_and_determinism():
    from dsi_tpu.ckpt.fault import chaos_decision, parse_chaos_spec

    assert parse_chaos_spec("0.25") == (0.25, 0)
    assert parse_chaos_spec("0.25,42") == (0.25, 42)
    assert parse_chaos_spec("bogus") == (0.0, 0)
    assert parse_chaos_spec("1.5") == (0.0, 0)  # out of range = off
    # deterministic: same (seed, index, draw) -> same decision; the
    # sequence varies across indices so a fleet doesn't die in lockstep
    seq_a = [chaos_decision(0.3, 42, "0", d) for d in range(1, 20)]
    seq_b = [chaos_decision(0.3, 42, "0", d) for d in range(1, 20)]
    seq_c = [chaos_decision(0.3, 42, "1", d) for d in range(1, 20)]
    assert seq_a == seq_b
    assert seq_a != seq_c
    assert any(seq_a) and not all(seq_a)


def test_chaos_kill_point_real_exit(tmp_path):
    from dsi_tpu.ckpt.fault import CHAOS_EXIT

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    prog = ("from dsi_tpu.ckpt.fault import chaos_kill_point\n"
            "chaos_kill_point('task')\n"
            "print('survived')\n")
    env["DSI_CHAOS_WORKER_KILL"] = "1.0,7"
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == CHAOS_EXIT
    assert "CHAOS" in r.stderr
    env["DSI_CHAOS_WORKER_KILL"] = "0.0"
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0 and "survived" in r.stdout


def test_classic_worker_loop_has_chaos_boundary():
    # the knob is wired at the classic worker's task boundary too
    import inspect

    from dsi_tpu.mr import worker

    assert "chaos_kill_point" in inspect.getsource(worker.worker_loop)


# ── the differential chaos harness (acceptance criteria) ───────────────


def test_differential_chaos_harness(tmp_path):
    """Forced straggler AND a real mid-shard worker kill: the backup
    dispatcher fires, every shard commits exactly once (zero duplicate
    commits), the killed shard's takeover resumes from a checkpoint
    (cursor > 0), and the merged output is byte-identical to the
    sequential oracle (shardrun --check exits 0)."""
    corpus = str(tmp_path / "corpus.txt")
    import random

    rnd = random.Random(11)
    vocab = ["".join(rnd.choice("abcdefghijklmnop") for _ in range(4))
             for _ in range(300)]
    with open(corpus, "w") as f:
        for _ in range(16000):
            f.write(" ".join(rnd.choice(vocab) for _ in range(8)) + "\n")
    wd = str(tmp_path / "wd")
    stats_json = str(tmp_path / "stats.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSI_MR_SOCKET"] = str(tmp_path / "mr.sock")
    # 1-device CPU workers: the harness's 8-vdev XLA_FLAGS would shrink
    # every shard to ~one step, starving the kill/straggler windows.
    env.pop("XLA_FLAGS", None)
    cmd = [sys.executable, "-m", "dsi_tpu.cli.shardrun",
           "--workers", "3", "--shards", "3", "--workdir", wd,
           "--chunk-bytes", "32768", "--ckpt-secs", "0.05",
           "--progress-s", "0.1", "--spec-floor", "2.0",
           "--shard-timeout", "8",
           "--slow-worker", "0:1.2",          # the forced straggler
           "--fault-worker", "1:mid-fold:6",  # the REAL os._exit kill
           "--check", "--stats-json", stats_json, corpus]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=420)
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "parity OK" in r.stderr
    with open(stats_json, encoding="utf-8") as f:
        s = json.load(f)
    assert s["commits"] == s["shards"] == 3
    assert s["duplicate_commits"] == 0
    assert s["backup_dispatches"] >= 1, r.stderr[-3000:]
    # the kill really happened (FAULT_EXIT path) and somebody resumed
    # from a durable checkpoint rather than replaying from zero
    assert "FAULT: injected crash" in r.stderr
    assert s["resumed_attempts"] >= 1, r.stderr[-3000:]
    assert any(v > 0 for v in s["resume_cursors"].values())
    # exactly one commit record per shard in the journal
    shard_records = {}
    with open(os.path.join(wd, "shards.journal"), encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "shard":
                shard_records[rec["task"]] = \
                    shard_records.get(rec["task"], 0) + 1
    assert shard_records == {0: 1, 1: 1, 2: 1}
    # losers reaped their partials: no .part litter survives
    assert not [n for n in os.listdir(wd) if n.endswith(".part")]
