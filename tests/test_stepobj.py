"""Resumable step-object tests (``parallel/stepobj.py``).

The four engines were lifted from run-to-completion functions onto
explicit ``{advance, confirm, checkpoint, restore, close}`` state
machines (the serving daemon's substrate).  The legacy functions are
now construct-drive-close wrappers, so the existing parity grids
already pin the wrapped path; these tests pin what is NEW:

* manual lifecycle driving (advance/confirm interleaving, mid-stream
  confirm leaving an empty window, forced checkpoint) is bit-identical
  to the one-shot function for every engine;
* ``suspend()`` (the eviction primitive) + a fresh ``resume=True``
  construction reproduces the uninterrupted result byte-for-byte;
* the wave walks' word-window rung restart happens INSIDE ``advance``;
* host-path routing still returns None through the lifecycle.
"""

import os

import pytest

jax = pytest.importorskip("jax")

from dsi_tpu.parallel.grepstream import (GrepStep, IndexerStep,
                                         grep_streaming,
                                         indexer_streaming)
from dsi_tpu.parallel.shuffle import default_mesh
from dsi_tpu.parallel.streaming import WordcountStep, wordcount_streaming
from dsi_tpu.parallel.tfidf import TfidfStep, tfidf_sharded

MESH = None


def mesh():
    global MESH
    if MESH is None:
        MESH = default_mesh(8)
    return MESH


TEXT = ("alpha beta gamma delta epsilon the quick brown fox "
        "jumps over the lazy dog " * 300).encode()
DOCS = [b"alpha beta alpha gamma", b"beta delta beta",
        b"gamma the fox jumps", b"delta dog lazy the the",
        b"epsilon alpha quick brown"]


def drive(step):
    while step.advance():
        pass
    return step.close()


@pytest.mark.parametrize("device_accumulate", [False, True])
def test_wordcount_step_manual_drive_bit_identical(device_accumulate):
    want = wordcount_streaming([TEXT], mesh=mesh(), n_reduce=4,
                               chunk_bytes=1 << 11, u_cap=1 << 9,
                               device_accumulate=device_accumulate)
    assert want is not None
    step = WordcountStep([TEXT], mesh=mesh(), n_reduce=4,
                         chunk_bytes=1 << 11, u_cap=1 << 9,
                         device_accumulate=device_accumulate)
    # Interleave: a few advances, a mid-stream confirm (drains the
    # window to a consistent boundary), then more advances.
    assert step.advance()
    assert step.advance()
    n = step.confirm()
    assert step._pipe.inflight == 0
    assert n == step.confirmed
    got = drive(step)
    assert got == want
    assert step.phase == "done"
    # close() is idempotent.
    assert step.close() == want


def test_wordcount_step_forced_checkpoint_and_suspend_resume(tmp_path):
    ckdir = str(tmp_path / "ck")
    want = wordcount_streaming([TEXT], mesh=mesh(), n_reduce=4,
                               chunk_bytes=1 << 8, u_cap=1 << 9)
    step = WordcountStep([TEXT], mesh=mesh(), n_reduce=4,
                         chunk_bytes=1 << 8, u_cap=1 << 9,
                         checkpoint_dir=ckdir, checkpoint_every=1000,
                         checkpoint_delta=True)
    for _ in range(3):
        assert step.advance()
    # Forced checkpoint at a confirmed boundary (cadence would never
    # fire at every=1000): a durable manifest must exist right after.
    assert step.checkpoint() is True
    assert any(n.startswith("manifest-") for n in os.listdir(ckdir))
    assert step.advance()
    # Evict: suspend commits a snapshot and kills the object.
    assert step.suspend() is True
    assert step.phase == "suspended"
    assert step.close() is None  # a suspended step has no result
    # A fresh resume=True construction continues the chain.
    pstats = {}
    resumed = WordcountStep([TEXT], mesh=mesh(), n_reduce=4,
                            chunk_bytes=1 << 8, u_cap=1 << 9,
                            checkpoint_dir=ckdir, checkpoint_every=1000,
                            checkpoint_delta=True, resume=True,
                            pipeline_stats=pstats)
    assert resumed.restore().get("resume_cursor", 0) > 0
    got = drive(resumed)
    assert got == want
    assert pstats["resume_cursor"] > 0


def test_wordcount_step_hostpath_routes_none():
    step = WordcountStep(["caf\xe9 latte".encode("utf-8")], mesh=mesh(),
                         n_reduce=4, chunk_bytes=1 << 11, u_cap=1 << 9)
    assert drive(step) is None
    assert step.phase == "hostpath"


def test_wordcount_step_forced_widen_parity(monkeypatch):
    # A tiny device-table rung + a wide vocabulary force the mid-stream
    # widen protocol through the step lifecycle.
    import numpy as np

    monkeypatch.setenv("DSI_DEVICE_TABLE_CAP", "32")
    vocab = [f"{chr(97 + i % 26)}{chr(97 + (i // 26) % 26)}"
             f"{chr(97 + (i // 676) % 26)}x" for i in range(500)]
    rng = np.random.default_rng(7)
    blocks = [(" ".join(vocab[j] for j in rng.integers(0, 500, 300))
               + "\n").encode() for _ in range(8)]
    want = wordcount_streaming(list(blocks), mesh=mesh(), n_reduce=10,
                               chunk_bytes=1 << 11, u_cap=64,
                               device_accumulate=True, sync_every=3)
    pstats = {}
    step = WordcountStep(list(blocks), mesh=mesh(), n_reduce=10,
                         chunk_bytes=1 << 11, u_cap=64,
                         device_accumulate=True, sync_every=3,
                         pipeline_stats=pstats)
    assert drive(step) == want
    assert pstats.get("widens", 0) >= 1


def test_grep_step_manual_drive_and_suspend_resume(tmp_path):
    blocks = [b"the fox\nno match here\nthe the the\nfoxes the\n" * 800]
    want = grep_streaming(blocks, "the", mesh=mesh(),
                          chunk_bytes=1 << 9)
    assert want is not None
    ckdir = str(tmp_path / "gck")
    step = GrepStep(blocks, "the", mesh=mesh(), chunk_bytes=1 << 9,
                    checkpoint_dir=ckdir, checkpoint_every=1,
                    checkpoint_delta=True)
    assert step.advance()
    assert step.advance()
    assert step.suspend() is True
    resumed = GrepStep(blocks, "the", mesh=mesh(), chunk_bytes=1 << 9,
                       checkpoint_dir=ckdir, checkpoint_every=1,
                       checkpoint_delta=True, resume=True)
    assert drive(resumed) == want


def test_grep_step_non_literal_pattern_is_terminal():
    step = GrepStep([b"anything\n"], "a|b", mesh=mesh())
    assert step.phase == "hostpath"
    assert step.advance() is False
    assert step.close() is None


def test_tfidf_step_manual_drive_with_rung_restart():
    # One >16-byte word forces the 64-byte rung restart INSIDE the
    # lifecycle: advance() must tear the rung down and keep going.
    docs = list(DOCS) + [b"supercalifragilisticexpialidocious word"]
    want = tfidf_sharded(docs, mesh=mesh(), n_reduce=4, u_cap=1 << 8)
    assert want is not None
    stats = {}
    step = TfidfStep(docs, mesh=mesh(), n_reduce=4, u_cap=1 << 8,
                     wave_stats=stats)
    assert drive(step) == want
    assert step.phase == "done"


def test_indexer_step_manual_drive_bit_identical():
    want = indexer_streaming(DOCS, mesh=mesh(), n_reduce=4,
                             u_cap=1 << 8)
    assert want is not None
    step = IndexerStep(DOCS, mesh=mesh(), n_reduce=4, u_cap=1 << 8)
    assert step.advance()
    step.confirm()
    got = drive(step)
    assert got == want


@pytest.mark.parametrize("mesh_shards", [0, 8])
def test_wordcount_step_mesh_parity(mesh_shards):
    want = wordcount_streaming([TEXT], mesh=mesh(), n_reduce=4,
                               chunk_bytes=1 << 11, u_cap=1 << 9,
                               mesh_shards=mesh_shards)
    step = WordcountStep([TEXT], mesh=mesh(), n_reduce=4,
                         chunk_bytes=1 << 11, u_cap=1 << 9,
                         mesh_shards=mesh_shards)
    assert drive(step) == want
