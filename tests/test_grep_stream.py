"""Streaming grep / indexer engines (parallel/grepstream.py) and the
on-device top-k/histogram service (device/topk.py).

Oracle discipline as everywhere else: every engine path — depth x
device_accumulate x forced l_cap replay x forced top-k widen — must
agree BIT-FOR-BIT with the depth=1 host-merge path and with a
pure-Python oracle over the same bytes (including per-word posting
order for the indexer), so any divergence is an engine/service bug,
never a tolerance.
"""

import re

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.parallel.grepstream import (
    GrepStreamResult,
    batch_lines,
    grep_host_oracle,
    grep_streaming,
    indexer_streaming,
    write_indexer_output,
    _LineTooLong,
)
from dsi_tpu.parallel.shuffle import default_mesh

WORDS = re.compile(r"[A-Za-z]+")


def _mesh():
    return default_mesh(8)


def _letters(i: int) -> str:
    return "".join(chr(97 + (i // 26 ** j) % 26) for j in range(3))


VOCAB = [_letters(i) for i in range(600)]


# ── batch_lines ────────────────────────────────────────────────────────


def test_batch_lines_cuts_only_at_newlines():
    blocks = [b"alpha\nbeta\n", b"gam", b"ma\ndelta\nepsilon"]
    batches = list(batch_lines(blocks, n_dev=2, chunk_bytes=8))
    text = b""
    total_lines = 0
    for batch, lens, row_lines in batches:
        for d in range(2):
            row = bytes(batch[d, :lens[d]])
            assert not batch[d, lens[d]:].any()  # zero tail
            # no line straddles a row: every non-final row ends in \n
            text += row
            total_lines += int(row_lines[d])
    assert text == b"".join(blocks)
    # 5 lines, the last unterminated
    assert total_lines == 5


def test_batch_lines_line_wider_than_chunk_raises():
    with pytest.raises(_LineTooLong):
        list(batch_lines([b"x" * 100], n_dev=2, chunk_bytes=16))


def test_batch_lines_exact_chunk_final_line_fits():
    # A final unterminated line of exactly chunk_bytes must NOT raise.
    batches = list(batch_lines([b"y" * 16], n_dev=1, chunk_bytes=16))
    assert len(batches) == 1
    batch, lens, row_lines = batches[0]
    assert int(lens[0]) == 16 and int(row_lines[0]) == 1


# ── grep: oracle + host path ───────────────────────────────────────────


def _grep_blocks(seed: int, n_blocks: int = 8):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_blocks):
        words = [VOCAB[j] for j in rng.integers(0, 400, 120)]
        lines = []
        cur = []
        for w in words:
            cur.append(w)
            if rng.random() < 0.2:
                lines.append(" ".join(cur))
                cur = []
        lines.append(" ".join(cur))
        out.append(("\n".join(lines) + "\n").encode())
    return out


def test_grep_host_path_matches_oracle():
    blocks = _grep_blocks(1)
    want = grep_host_oracle(list(blocks), "aba")
    st: dict = {}
    res = grep_streaming(list(blocks), "aba", mesh=_mesh(),
                         chunk_bytes=1 << 11, depth=2, pipeline_stats=st)
    assert res == want
    assert isinstance(res, GrepStreamResult)
    assert st["step_pulls"] >= 1 and st["sync_pulls"] == 0
    assert sum(res.hist) == res.lines  # every line lands in one bucket


def test_grep_overlapping_occurrences_counted():
    # 'aa' in 'aaaa' occurs 3 times (overlapping) — engine and oracle
    # must agree on the overlap rule.
    blocks = [b"aaaa\naa\nxx\n"]
    want = grep_host_oracle(list(blocks), "aa")
    assert want.occurrences == 4 and want.matched == 2
    res = grep_streaming(list(blocks), "aa", mesh=_mesh(),
                         chunk_bytes=1 << 11, depth=1)
    assert res == want


def test_grep_host_path_rejections():
    mesh = _mesh()
    # non-literal pattern: the regex tiers' job, not this engine's
    assert grep_streaming([b"x\n"], "th.e", mesh=mesh,
                          chunk_bytes=1 << 11) is None
    # a line wider than the chunk: host path
    assert grep_streaming([b"z" * 5000], "z", mesh=mesh,
                          chunk_bytes=1 << 11) is None
    # empty stream: zeros, not None
    res = grep_streaming([], "the", mesh=mesh, chunk_bytes=1 << 11)
    assert res.lines == 0 and res.matched == 0 and res.topk == ()


# ── grep: the parity grid ──────────────────────────────────────────────


def test_grep_parity_grid_depth_x_device_accumulate():
    """depth x device_accumulate x K bit-identical to the depth=1
    host-merge path (and to the oracle)."""
    blocks = _grep_blocks(7)
    mesh = _mesh()
    want = grep_host_oracle(list(blocks), "aba")
    base = grep_streaming(list(blocks), "aba", mesh=mesh,
                          chunk_bytes=1 << 11, depth=1)
    assert base == want
    for depth in (1, 3):
        for dacc, k in ((False, None), (True, 1), (True, 4)):
            st: dict = {}
            res = grep_streaming(list(blocks), "aba", mesh=mesh,
                                 chunk_bytes=1 << 11, depth=depth,
                                 device_accumulate=dacc, sync_every=k,
                                 pipeline_stats=st)
            assert res == base, (depth, dacc, k)
            if dacc:
                assert st["step_pulls"] == 0


def test_grep_forced_l_cap_replay_sticky():
    """Short lines overflow the optimistic avg-line>=8B rung: the step
    replays at the n+1 hard bound through the pipeline (NOT the host
    fallback), the wider rung sticks, and results stay bit-identical."""
    blocks = [b"a\n" * 2000, b"aba\nx\n" * 500, b"a\n" * 2000]
    mesh = _mesh()
    want = grep_host_oracle(list(blocks), "aba")
    st: dict = {}
    res = grep_streaming(list(blocks), "aba", mesh=mesh,
                         chunk_bytes=1 << 11, depth=2, pipeline_stats=st)
    assert res == want
    assert st["replays"] >= 1
    assert st["l_cap"] == (1 << 11) + 1  # the hard-bound rung stuck
    # ...and exactly once per overflowing step, not once per later step:
    assert st["replays"] <= st["steps"]
    # same stream through the device services, same answer
    st2: dict = {}
    res2 = grep_streaming(list(blocks), "aba", mesh=mesh,
                          chunk_bytes=1 << 11, depth=2,
                          device_accumulate=True, sync_every=2,
                          pipeline_stats=st2)
    assert res2 == want
    assert st2["replays"] >= 1 and st2["step_pulls"] == 0


def test_grep_forced_topk_widen_never_drops(monkeypatch):
    """A candidate table forced to a tiny rung overflows mid-stream:
    the fold no-ops, the service drains + widens + re-folds, and the
    final top-k is still bit-identical — overflow surfaces a widen
    signal, it never drops candidates."""
    monkeypatch.setenv("DSI_DEVICE_TOPK_CAP", "32")
    blocks = [(" aba x" * 8 + "\n").encode() * 30] * 60
    mesh = _mesh()
    want = grep_host_oracle(list(blocks), "aba")
    st: dict = {}
    res = grep_streaming(list(blocks), "aba", mesh=mesh,
                         chunk_bytes=1 << 11, depth=2,
                         device_accumulate=True, sync_every=3,
                         pipeline_stats=st)
    assert res == want
    assert st["widens"] >= 1 and st["fold_overflows"] >= 1
    assert st["step_pulls"] == 0
    assert st["table_cap"] > 32  # the rung actually moved


def test_grep_sync_accounting_windows_plus_close():
    """Device path accounting: zero per-step pulls; one snapshot+hist
    pull bundle per K confirmed folds plus the close drain — the
    ceil(steps/K)+widens amortization the service exists for."""
    line = (" ".join(VOCAB[:30]) + " aba\n").encode() * 6
    blocks = [line] * 400  # ~290 KB -> ~18 steps of 8 x 2 KiB
    mesh = _mesh()
    for k in (3, 8):
        st: dict = {}
        res = grep_streaming(list(blocks), "aba", mesh=mesh,
                             chunk_bytes=1 << 11, depth=2,
                             device_accumulate=True, sync_every=k,
                             pipeline_stats=st)
        assert res is not None and res.matched > 0
        assert st["step_pulls"] == 0 and st["widens"] == 0
        windows = st["folds"] // k
        assert st["folds"] == st["steps"] >= k
        assert st["sync_pulls"] == windows + 1  # windows + close drain
        assert st["hist_pulls"] == windows + 1
        assert st["topk_snapshots"] == windows


def test_grep_property_random_streams():
    """Property: random streams x random K x both paths, equal to the
    oracle and to each other."""
    mesh = _mesh()
    for seed in (11, 29):
        rng = np.random.default_rng(seed)
        blocks = _grep_blocks(seed, n_blocks=int(rng.integers(3, 7)))
        pat = ["aba", "ab", "aaa"][int(rng.integers(0, 3))]
        k = int(rng.integers(1, 6))
        want = grep_host_oracle(list(blocks), pat)
        res = grep_streaming(list(blocks), pat, mesh=mesh,
                             chunk_bytes=1 << 11, depth=2,
                             device_accumulate=True, sync_every=k)
        assert res == want, (seed, pat, k)


# ── indexer ────────────────────────────────────────────────────────────


def _idx_docs(n_docs: int, seed: int):
    rng = np.random.default_rng(seed)
    return [(" ".join(VOCAB[j] for j in
                      rng.integers(0, 180, int(rng.integers(30, 120))))
             + "\n").encode() for _ in range(n_docs)]


def _idx_oracle(docs):
    """{word: sorted doc list} + {word: df} from the host tokenizer."""
    posts: dict = {}
    for d, doc in enumerate(docs):
        for w in sorted(set(WORDS.findall(doc.decode()))):
            posts.setdefault(w, []).append(d)
    return posts


def test_indexer_matches_oracle_and_posting_order():
    mesh = _mesh()
    docs = _idx_docs(13, seed=5)
    want = _idx_oracle(docs)
    st: dict = {}
    base = indexer_streaming(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                             depth=1, stats=st)
    assert base is not None
    postings, top = base
    assert set(postings) == set(want)
    for w, docs_w in want.items():
        # doc SETS match the oracle; ORDER is the wave order, stable
        assert sorted(postings[w][1]) == docs_w, w
    # df top-k: count desc, word asc, exact
    df = {w: len(ds) for w, ds in want.items()}
    want_top = tuple(sorted(((c, w) for w, c in df.items()),
                            key=lambda r: (-r[0], r[1]))[:16])
    assert top == want_top
    assert st["step_pulls"] >= 1


def test_indexer_parity_grid_bit_identical():
    """depth x device_accumulate x K: identical postings (per-word doc
    ORDER included) and identical df top-k."""
    mesh = _mesh()
    docs = _idx_docs(21, seed=9)
    base = indexer_streaming(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                             depth=1)
    assert base is not None
    for depth in (1, 3):
        for dacc, k in ((False, None), (True, 2), (True, 7)):
            st: dict = {}
            res = indexer_streaming(docs, mesh=mesh, n_reduce=10,
                                    u_cap=1 << 9, depth=depth,
                                    device_accumulate=dacc, sync_every=k,
                                    stats=st)
            assert res is not None
            assert res == base, (depth, dacc, k)
            if dacc:
                assert st["step_pulls"] == 0
                assert st["appends"] >= 1 and st["folds"] >= 1


def test_indexer_forced_topk_widen(monkeypatch):
    """The df table forced below the vocabulary widens mid-walk and the
    result is still bit-identical — same acceptance as the stream's
    fold table."""
    monkeypatch.setenv("DSI_DEVICE_TOPK_CAP", "32")
    mesh = _mesh()
    docs = _idx_docs(16, seed=3)
    base = indexer_streaming(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                             depth=1)
    st: dict = {}
    res = indexer_streaming(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                            depth=2, device_accumulate=True, sync_every=2,
                            stats=st)
    assert base is not None and res is not None
    assert res == base
    assert st["widens"] >= 1 and st["fold_overflows"] >= 1
    assert st["step_pulls"] == 0


def test_indexer_forced_postings_overflow(monkeypatch):
    """A postings buffer trimmed below the window drains early (the
    sticky-dirty order-preserving recovery) while the df folds ride the
    same confirmations — nothing lost, nothing doubled, order intact."""
    monkeypatch.setenv("DSI_DEVICE_POSTINGS_CAP", "256")
    mesh = _mesh()
    docs = _idx_docs(40, seed=13)
    base = indexer_streaming(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                             depth=1)
    st: dict = {}
    res = indexer_streaming(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                            depth=2, device_accumulate=True,
                            sync_every=10_000, stats=st)
    assert base is not None and res is not None
    assert res == base
    assert st["append_overflows"] >= 1


def test_indexer_host_path_rejections():
    mesh = _mesh()
    # non-ASCII: the host app's job
    assert indexer_streaming(["caf\xe9".encode("utf-8")], mesh=mesh,
                             n_reduce=10, u_cap=1 << 9) is None
    # a word wider than 64 bytes: host path
    assert indexer_streaming([b"x" * 80 + b" y"], mesh=mesh, n_reduce=10,
                             u_cap=1 << 9) is None


def test_write_indexer_output_matches_host_app_format(tmp_path):
    """mr-out-* files byte-identical to the sequential indexer app over
    the same documents."""
    from dsi_tpu.apps import indexer as app
    from dsi_tpu.mr.sequential import run_sequential
    from tests.harness import merged_output

    docs = _idx_docs(6, seed=21)
    names = []
    for i, doc in enumerate(docs):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(doc)
        names.append(str(p))
    res = indexer_streaming(docs, mesh=_mesh(), n_reduce=10, u_cap=1 << 9)
    assert res is not None
    wd = tmp_path / "out"
    wd.mkdir()
    write_indexer_output(res, names, 10, str(wd))
    oracle_out = tmp_path / "mr-correct.txt"
    run_sequential(app.Map, app.Reduce, names, str(oracle_out))
    with open(oracle_out, encoding="utf-8") as f:
        want = sorted(l for l in f if l.strip())
    assert merged_output(str(wd)) == want


# ── warm ladder / AOT coverage ─────────────────────────────────────────


def test_grep_warm_covers_everything(tmp_path, monkeypatch):
    """warm_grepstream_aot(device_accumulate=True) must pre-compile
    every program a device-accumulated aot run then executes — both
    l_cap rungs, the top-k fold/pack/snapshot shapes, the histogram
    fold — so a chip run is loads, never compiles."""
    from dsi_tpu.backends import aotcache
    from dsi_tpu.parallel.grepstream import (grepstream_persisted,
                                             warm_grepstream_aot)

    monkeypatch.setenv("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    mesh = default_mesh(1)
    warm_grepstream_aot(mesh=mesh, chunk_bytes=1 << 14,
                        device_accumulate=True)
    # The persisted probe itself answers False in this 8-virtual-device
    # process BY DESIGN (is_persisted mirrors cached_compile's load
    # policy: deserialized executables reject multi-device args), so the
    # no-new-compiles assertion below is the coverage check here — the
    # same discipline as the stream engine's warm test.
    assert not grepstream_persisted(mesh=mesh, chunk_bytes=1 << 14,
                                    device_accumulate=True)
    compiles_after_warm = aotcache.stats["compiles"]
    blocks = [b"the quick fox\nthe end\n" * 200] * 3
    want = grep_host_oracle(list(blocks), "the")
    st: dict = {}
    res = grep_streaming(list(blocks), "the", mesh=mesh,
                         chunk_bytes=1 << 14, depth=2, aot=True,
                         device_accumulate=True, sync_every=2,
                         pipeline_stats=st)
    assert res == want
    assert st["folds"] >= 1 and st["step_pulls"] == 0
    assert aotcache.stats["compiles"] == compiles_after_warm


# ── unified cold-compile knob ──────────────────────────────────────────


def test_cold_ok_unified_knob_and_aliases(monkeypatch):
    from dsi_tpu.ops.grepk import cold_ok

    for var in ("DSI_COLD_OK", "DSI_GREP_COLD_OK", "DSI_NFA_COLD_OK"):
        monkeypatch.delenv(var, raising=False)
    assert not cold_ok()
    for var in ("DSI_COLD_OK", "DSI_GREP_COLD_OK", "DSI_NFA_COLD_OK"):
        monkeypatch.setenv(var, "1")
        assert cold_ok(), var
        monkeypatch.delenv(var)


# ── CLI ────────────────────────────────────────────────────────────────


def test_grepstream_cli_check_against_oracle(tmp_path):
    """The engine is reachable without importing internals: grepstream
    --check end-to-end (device-accumulated) vs the host oracle."""
    from dsi_tpu.cli import grepstream as cli
    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2,
                          file_size=20_000)
    rc = cli.main(["--pattern", "the", "--chunk-bytes", "4096",
                   "--check", "--device-accumulate", "--sync-every", "4",
                   "--topk", "8"] + files)
    assert rc == 0  # --check exits 2 on a parity failure
