"""Raft core state-machine tests (6.5840 Lab-2 style, ISSUE 20).

Everything here runs the DETERMINISTIC core alone: an in-memory
message bus, a hand-advanced clock, and stub rngs with scripted
election timeouts — no sockets, no threads, no jax.  The scenarios are
the acceptance list: split vote, partition (a cut-off old leader can
never finalize a commit), log divergence + truncation healing, and
stale-term rejection.
"""

import random

import pytest

from dsi_tpu.replica.raft import (APPEND, CANDIDATE, FOLLOWER, LEADER,
                                  NOOP, RaftCore, VOTE_REQ)
from dsi_tpu.replica.rlog import RaftStore


class ScriptedRng:
    """uniform() returns scripted values, then a fixed fallback —
    the knob that forces simultaneous (split-vote) or ordered
    (deterministic-winner) election timeouts."""

    def __init__(self, values, fallback=0.25):
        self.values = list(values)
        self.fallback = fallback

    def uniform(self, a, b):
        v = self.values.pop(0) if self.values else self.fallback
        return max(a, min(b, v))


class Net:
    """In-memory bus: collects outbound messages, delivers them in
    order, honors a partition set of unreachable node ids."""

    def __init__(self, nodes):
        self.nodes = nodes
        self.queue = []
        self.dead = set()
        self.cut = set()  # node ids isolated from everyone else

    def _reachable(self, a, b):
        if a in self.dead or b in self.dead:
            return False
        return (a in self.cut) == (b in self.cut) \
            if (a in self.cut or b in self.cut) else True

    def send(self, msgs):
        self.queue.extend(msgs)

    def deliver_all(self, now, max_rounds=100):
        rounds = 0
        while self.queue and rounds < max_rounds:
            rounds += 1
            batch, self.queue = self.queue, []
            for m in batch:
                if not self._reachable(m["from"], m["to"]):
                    continue
                self.send(self.nodes[m["to"]].on_message(m, now))
        assert not self.queue or rounds < max_rounds, \
            "message storm did not quiesce"

    def tick_all(self, now):
        for n in self.nodes:
            if n.node_id not in self.dead:
                self.send(n.tick(now))

    def leaders(self):
        return [n for n in self.nodes
                if n.role == LEADER and n.node_id not in self.dead]


def cluster(n=3, timeouts=None, stores=None):
    """Build an n-node cluster; ``timeouts[i]`` scripts node i's FIRST
    election timeout (later draws fall back to 0.25s)."""
    nodes = []
    for i in range(n):
        rng = ScriptedRng([timeouts[i]] if timeouts else [],
                          fallback=0.25) if timeouts \
            else ScriptedRng([], fallback=0.20 + 0.03 * i)
        nodes.append(RaftCore(i, n, rng=rng, now=0.0,
                              store=stores[i] if stores else None))
    return Net(nodes)


def elect(net, now=0.2):
    """Drive one election round to completion; returns the leader."""
    net.tick_all(now)
    net.deliver_all(now)
    leaders = net.leaders()
    assert len(leaders) == 1, [n.status() for n in net.nodes]
    return leaders[0]


def commit(net, leader, data, now):
    idx, msgs = leader.propose(data, now)
    assert idx is not None
    net.send(msgs)
    net.deliver_all(now)
    return idx


def test_first_timeout_wins_election():
    net = cluster(3, timeouts=[0.15, 0.25, 0.25])
    lead = elect(net)
    assert lead.node_id == 0 and lead.current_term == 1
    # Followers learned the leader (the NotLeader redirect hint).
    for n in net.nodes[1:]:
        assert n.role == FOLLOWER and n.leader_id == 0


def test_split_vote_resolves_next_round():
    # All three time out at once: each votes for itself, nobody
    # reaches majority this term.
    net = cluster(3, timeouts=[0.15, 0.15, 0.15])
    net.tick_all(0.2)
    assert all(n.role == CANDIDATE and n.current_term == 1
               for n in net.nodes)
    net.deliver_all(0.2)
    assert net.leaders() == []  # the split vote
    assert all(n.voted_for == n.node_id for n in net.nodes)
    # Next timeouts are the scripted fallbacks (0.25 each) — stagger
    # them by re-scripting node 2 shorter so the retry is decisive.
    net.nodes[2].rng.values = [0.10]
    net.nodes[2]._election_due = 0.2 + net.nodes[2].rng.uniform(0, 1)
    net.tick_all(0.35)
    net.deliver_all(0.35)
    leaders = net.leaders()
    assert [lead.node_id for lead in leaders] == [2]
    assert leaders[0].current_term == 2


def test_stale_term_candidate_and_leader_rejected():
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    # A vote request from a STALE term is refused and the refusal
    # carries the newer term.
    stale = {"type": VOTE_REQ, "from": 1, "to": 0, "term": 0,
             "last_log_index": 0, "last_log_term": 0}
    out = lead.on_message(stale, 1.1)
    assert out and out[0]["granted"] is False \
        and out[0]["term"] == lead.current_term
    # A stale-term APPEND is refused too (an old leader's heartbeat
    # after a new election cannot reset anyone's timer).
    f = net.nodes[1]
    out = f.on_message({"type": APPEND, "from": 2, "to": 1, "term": 0,
                        "prev_index": 0, "prev_term": 0, "entries": [],
                        "commit": 0}, 1.1)
    assert out and out[0]["ok"] is False \
        and out[0]["term"] == f.current_term
    # And the old leader steps down the moment any newer term reaches it.
    lead.on_message({"type": APPEND, "from": 1, "to": 0,
                     "term": lead.current_term + 5, "prev_index": 0,
                     "prev_term": 0, "entries": [], "commit": 0}, 1.2)
    assert lead.role == FOLLOWER


def test_commit_requires_majority_and_survives_failover():
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    idx = commit(net, lead, {"kind": "shard", "task": 0}, 1.1)
    assert lead.commit_index >= idx
    # One heartbeat propagates the advanced commit index to followers.
    net.tick_all(1.2)
    net.deliver_all(1.2)
    # Every node delivers the SAME committed sequence exactly once.
    seqs = [[d for _, d in n.take_committed()] for n in net.nodes]
    for s in seqs[1:]:
        assert s == seqs[0]
    assert {"kind": "shard", "task": 0} in seqs[0]
    # Leader dies; a follower wins and the committed entry is still
    # there (leader-completeness).
    net.dead.add(lead.node_id)
    net.nodes[1].rng.values = [0.1]
    net.nodes[1]._election_due = 1.2
    net.nodes[2]._election_due = 99.0
    net.tick_all(1.3)
    net.deliver_all(1.3)
    lead2 = net.leaders()[0]
    assert lead2.node_id != lead.node_id
    assert lead2.current_term > lead.current_term
    assert any(e["data"] == {"kind": "shard", "task": 0}
               for e in lead2.log)


def test_partitioned_old_leader_cannot_finalize():
    """THE exactly-once arbitration property: a leader cut off from the
    majority can never advance commit_index, while the majority side
    elects a new leader, commits, and on heal the old leader's
    unreplicated tail is truncated away."""
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    commit(net, lead, {"op": "pre"}, 1.1)
    base_commit = lead.commit_index
    # Partition the leader alone; it keeps proposing into the void.
    net.cut = {lead.node_id}
    idx, msgs = lead.propose({"op": "lost-a"}, 1.2)
    net.send(msgs)
    lead.propose({"op": "lost-b"}, 1.25)
    net.tick_all(1.3)
    net.deliver_all(1.3)
    assert lead.commit_index == base_commit  # no majority, no finality
    # Majority side elects node 1.
    net.nodes[1].rng.values = [0.1]
    net.nodes[1]._election_due = 1.3
    net.nodes[2]._election_due = 99.0
    net.tick_all(1.45)
    net.deliver_all(1.45)
    lead2 = [n for n in net.leaders() if n.node_id != lead.node_id][0]
    commit(net, lead2, {"op": "won"}, 1.5)
    committed_new = [d for _, d in lead2.take_committed()]
    assert {"op": "won"} in committed_new
    assert not any(d == {"op": "lost-a"} for d in committed_new)
    # Heal: the old leader rejoins, steps down, truncates its divergent
    # suffix, and converges on the new leader's log.
    net.cut = set()
    net.tick_all(1.6)
    net.deliver_all(1.6)
    assert lead.role == FOLLOWER
    assert [e["data"] for e in lead.log] == [e["data"] for e in lead2.log]
    old_committed = [d for _, d in lead.take_committed()]
    assert not any(d in ({"op": "lost-a"}, {"op": "lost-b"})
                   for d in old_committed)
    assert {"op": "won"} in old_committed


def test_log_divergence_truncation():
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    # Follower 1 grows a divergent uncommitted suffix (as if an old
    # leader appended locally before dying).
    f = net.nodes[1]
    f.log.append({"term": 0, "data": {"op": "phantom-1"}})
    f.log.append({"term": 0, "data": {"op": "phantom-2"}})
    commit(net, lead, {"op": "real"}, 1.1)
    # Heartbeats heal the divergence: phantom entries are gone and the
    # follower's log byte-matches the leader's.
    net.tick_all(1.2)
    net.deliver_all(1.2)
    assert [e["data"] for e in f.log] == [e["data"] for e in lead.log]
    assert not any(e["data"].get("op", "").startswith("phantom")
                   for e in f.log)


def test_vote_refused_for_stale_log():
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    commit(net, lead, {"op": "x"}, 1.1)
    # Node 2 wipes its log (stale disk) and campaigns: refused by both,
    # because leader-completeness forbids electing a short log.
    stale = net.nodes[2]
    stale.log = []
    stale.rng.values = [0.05]
    stale._election_due = 1.2
    net.send(stale.tick(1.3))
    net.deliver_all(1.3)
    assert stale.role != LEADER


def test_noop_commits_inherited_entries():
    """A new leader's no-op (its own term) is how entries inherited
    from a dead leader become committable (§5.4.2)."""
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    # Replicate an entry WITHOUT committing it anywhere: deliver the
    # appends but drop the responses.
    idx, msgs = lead.propose({"op": "inherited"}, 1.1)
    for m in msgs:
        net.nodes[m["to"]].on_message(m, 1.1)  # responses discarded
    assert lead.commit_index < idx
    net.dead.add(lead.node_id)
    net.nodes[1].rng.values = [0.1]
    net.nodes[1]._election_due = 1.2
    net.nodes[2]._election_due = 99.0
    net.tick_all(1.35)
    net.deliver_all(1.35)
    lead2 = net.leaders()[0]
    assert lead2.commit_index >= idx + 1  # inherited entry + its no-op
    datas = [d for _, d in lead2.take_committed()]
    assert {"op": "inherited"} in datas
    assert dict(NOOP) in datas


def test_store_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "n0.rlog")
    st = RaftStore(path)
    term, voted, entries = st.load()
    assert (term, voted, entries) == (0, None, [])
    st.save_term(3, 1)
    st.append(1, [{"term": 2, "data": {"op": "a"}},
                  {"term": 3, "data": {"op": "b"}}])
    st.truncate(2)
    st.append(2, [{"term": 3, "data": {"op": "c"}}])
    st.close()
    term, voted, entries = RaftStore(path).load()
    assert term == 3 and voted == 1
    assert [e["data"]["op"] for e in entries] == ["a", "c"]
    # Torn tail: half a record appended by a crash is truncated away.
    with open(path, "ab") as f:
        f.write(b'{"kind": "entry", "index": 3, "te')
    term, voted, entries = RaftStore(path).load()
    assert [e["data"]["op"] for e in entries] == ["a", "c"]


def test_store_corrupt_middle_record_truncates(tmp_path):
    path = str(tmp_path / "n0.rlog")
    st = RaftStore(path)
    st.load()
    st.save_term(1, 0)
    st.append(1, [{"term": 1, "data": {"op": "keep"}},
                  {"term": 1, "data": {"op": "lose"}}])
    st.close()
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.splitlines(keepends=True)
    # Flip one byte inside the LAST entry's payload: the record CRC
    # must catch it and replay must stop (clean prefix), never yield a
    # silently different entry.
    bad = bytearray(lines[-1])
    i = bad.find(b"lose")
    bad[i] = ord("L")
    # dsicheck: allow[raw-write] test corrupts the file on purpose
    with open(path, "wb") as f:
        f.write(b"".join(lines[:-1]) + bytes(bad))
    term, voted, entries = RaftStore(path).load()
    assert [e["data"]["op"] for e in entries] == ["keep"]


def test_core_restart_from_store_keeps_vote_and_log(tmp_path):
    stores = [RaftStore(str(tmp_path / f"n{i}.rlog")) for i in range(3)]
    net = cluster(3, timeouts=[0.15, 0.3, 0.3], stores=stores)
    lead = elect(net)
    commit(net, lead, {"op": "durable"}, 1.1)
    for s in stores:
        s.close()
    # Reboot node 1 from disk: same term, and the committed entry is
    # in its log (it will be re-delivered once a leader re-commits).
    st = RaftStore(str(tmp_path / "n1.rlog"))
    n1 = RaftCore(1, 3, rng=random.Random(7), now=0.0, store=st)
    assert n1.current_term == net.nodes[1].current_term
    assert any(e["data"] == {"op": "durable"} for e in n1.log)


def test_exactly_once_delivery_per_node():
    net = cluster(3, timeouts=[0.15, 0.3, 0.3])
    lead = elect(net)
    for k in range(5):
        commit(net, lead, {"op": f"e{k}"}, 1.1 + 0.01 * k)
    # Heartbeats keep flowing; take_committed never re-delivers.
    first = [d for _, d in lead.take_committed()]
    net.tick_all(1.3)
    net.deliver_all(1.3)
    assert lead.take_committed() == []
    ops = [d["op"] for d in first if "op" in d]
    assert ops == [f"e{k}" for k in range(5)]


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_randomized_churn_single_leader_per_term(seed):
    """Fuzz: random ticks/partitions; invariant — at most one leader
    per term, and committed prefixes never disagree."""
    rng = random.Random(seed)
    net = cluster(3)
    for n in net.nodes:
        n.rng = random.Random(seed * 10 + n.node_id)
    now = 0.0
    seen_terms = {}
    committed = {i: [] for i in range(3)}
    for step in range(400):
        now += rng.uniform(0.01, 0.08)
        if rng.random() < 0.05:
            net.cut = {rng.randrange(3)} if rng.random() < 0.7 else set()
        net.tick_all(now)
        # Partitions drop in-flight traffic too.
        net.queue = [m for m in net.queue
                     if net._reachable(m["from"], m["to"])]
        net.deliver_all(now)
        lead = net.leaders()
        for n in lead:
            prev = seen_terms.setdefault(n.current_term, n.node_id)
            assert prev == n.node_id, \
                f"two leaders in term {n.current_term}"
            if rng.random() < 0.3:
                _, msgs = n.propose({"step": step}, now)
                net.send(msgs)
        for n in net.nodes:
            committed[n.node_id].extend(d for _, d in n.take_committed())
    # Committed sequences are prefixes of each other (state-machine
    # safety).
    seqs = sorted(committed.values(), key=len)
    for a, b in zip(seqs, seqs[1:]):
        assert b[:len(a)] == a
