"""Device-resident accumulator service (dsi_tpu/device/).

Oracle discipline as everywhere else: the device-accumulated paths must
agree BIT-FOR-BIT with the depth=1 host-merge paths and with a host
Counter over the Go tokenizer semantics — folds consume exactly the
confirmed per-step tables the host merge would, so any divergence is a
service bug, never a tolerance.
"""

import collections
import math
import re

import pytest

jax = pytest.importorskip("jax")

import numpy as np

from dsi_tpu.device import DeviceTable, SyncPolicy, sync_every_default
from dsi_tpu.parallel.merge import PackedCounts
from dsi_tpu.parallel.shuffle import (
    default_mesh,
    mapreduce_step,
    _slice_pack,
)
from dsi_tpu.parallel.streaming import wordcount_streaming

WORDS = re.compile(r"[A-Za-z]+")


def _mesh():
    return default_mesh(8)


def _letters(i: int) -> str:
    return "".join(chr(97 + (i // 26 ** j) % 26) for j in range(3))


VOCAB = [_letters(i) for i in range(800)]


def _counts(res):
    return {w: c for w, (c, _) in res.items()}


# ── SyncPolicy ─────────────────────────────────────────────────────────


def test_sync_policy_cadence_and_env_default(monkeypatch):
    p = SyncPolicy(3)
    for _ in range(2):
        p.note_fold()
        assert not p.due()
    p.note_fold()
    assert p.due()
    p.reset()
    assert not p.due()
    monkeypatch.setenv("DSI_STREAM_SYNC_EVERY", "5")
    assert sync_every_default() == 5
    assert sync_every_default(2) == 2  # explicit wins
    monkeypatch.setenv("DSI_STREAM_SYNC_EVERY", "junk")
    assert sync_every_default() == 8
    assert sync_every_default(0) == 1  # floored at the degenerate cadence


# ── DeviceTable unit: fold + widen against a hand-driven host merge ───


def _run_step(mesh, text: bytes, u_cap: int = 64):
    """One mapreduce_step over identical per-device chunks, packed the
    way the streaming engine hands steps to the fold."""
    n_dev = mesh.devices.size
    chunks_np = np.zeros((n_dev, 512), np.uint8)
    for d in range(n_dev):
        t = text[:512]
        chunks_np[d, :len(t)] = np.frombuffer(t, np.uint8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dsi_tpu.parallel.shuffle import AXIS

    chunks = jax.device_put(chunks_np, NamedSharding(mesh, P(AXIS, None)))
    keys, lens, cnts, parts, scal = mapreduce_step(
        chunks, n_dev=n_dev, n_reduce=10, max_word_len=16, u_cap=u_cap,
        mesh=mesh, t_cap_frac=4, grouper="sort")
    packed = _slice_pack(keys, lens, cnts, parts, mp=keys.shape[1])
    return packed, scal, np.asarray(scal)


def _host_merge(steps, kk=4):
    acc = PackedCounts()
    for packed, _, scal_np in steps:
        pn = np.asarray(packed)
        for d in range(pn.shape[0]):
            nu = int(scal_np[d, 0])
            r = pn[d, :nu]
            acc.add(r[:, :kk], r[:, kk], r[:, kk + 1], r[:, kk + 2])
    return acc.finalize()


def test_device_table_fold_matches_host_merge():
    mesh = _mesh()
    steps = [_run_step(mesh, (" ".join(VOCAB[o:o + 20]) + " ").encode())
             for o in (0, 10, 40)]
    stats: dict = {}
    acc = PackedCounts()
    tab = DeviceTable(mesh, kk=4, cap=8 * 64, acc=acc, lag=1, stats=stats)
    for p, s, snp in steps:
        tab.fold(p, s, snp)
    tab.close()
    assert acc.finalize() == _host_merge(steps)
    assert stats["folds"] == 3 and stats["widens"] == 0
    assert stats["sync_pulls"] == 1  # the close() drain, nothing else


def test_device_table_widen_never_drops_keys():
    """A rung-0 capacity far below the vocabulary: every fold overflows,
    the service drains + widens + re-folds, and the final counts still
    match the host merge exactly — overflow surfaces a widen signal, it
    never silently drops keys."""
    mesh = _mesh()
    steps = [_run_step(mesh, (" ".join(VOCAB[o:o + 20]) + " ").encode())
             for o in (0, 20, 40)]
    stats: dict = {}
    acc = PackedCounts()
    tab = DeviceTable(mesh, kk=4, cap=2, acc=acc, lag=2, stats=stats)
    for p, s, snp in steps:
        tab.fold(p, s, snp)
    tab.close()
    got = acc.finalize()
    assert got == _host_merge(steps)
    assert len(got) == 60
    assert stats["widens"] >= 1 and stats["fold_overflows"] >= 1
    assert stats["table_cap"] > 2  # the rung actually moved


# ── streaming integration ─────────────────────────────────────────────


def test_stream_sync_accounting_exactly_ceil_steps_over_k():
    """K-step sync accounting: with every step non-empty and no widens,
    host pulls == ceil(folds / K) — the amortization the subsystem
    exists for (vs one pull per step on the host-merge path)."""
    line = (" ".join(VOCAB[:40]) + "\n").encode() * 4
    blocks = [line] * 480  # ~300 KB -> ~19 steps of 8 x 2 KiB
    mesh = _mesh()
    for k in (3, 8):
        st: dict = {}
        res = wordcount_streaming(list(blocks), mesh=mesh, n_reduce=10,
                                  chunk_bytes=1 << 11, u_cap=64, depth=2,
                                  device_accumulate=True, sync_every=k,
                                  pipeline_stats=st)
        assert res is not None
        want = {w: c for w, c in collections.Counter(
            WORDS.findall((line * 480).decode())).items()}
        assert _counts(res) == want
        assert st["folds"] == st["steps"] >= 2 * k  # every step folded
        assert st["widens"] == 0 and st["step_pulls"] == 0
        assert st["sync_pulls"] == math.ceil(st["folds"] / k)


def test_stream_device_accumulate_bit_identical_to_host_merge():
    """depth x K parity grid against the depth=1 synchronous host-merge
    path: identical result DICTS (counts and partitions both)."""
    rng = np.random.default_rng(11)
    blocks = [(" ".join(VOCAB[j] for j in rng.integers(0, 300, 350))
               + "\n").encode() for _ in range(10)]
    text = b"".join(blocks)
    want = dict(collections.Counter(WORDS.findall(text.decode())))
    mesh = _mesh()
    base = wordcount_streaming(list(blocks), mesh=mesh, n_reduce=10,
                               chunk_bytes=1 << 11, u_cap=64, depth=1)
    assert base is not None and _counts(base) == want
    for depth in (1, 3):
        for k in (1, 4):
            st: dict = {}
            res = wordcount_streaming(
                list(blocks), mesh=mesh, n_reduce=10, chunk_bytes=1 << 11,
                u_cap=64, depth=depth, device_accumulate=True,
                sync_every=k, pipeline_stats=st)
            assert res is not None
            assert res == base, (depth, k)  # bit-identical, partitions too
            assert st["step_pulls"] == 0


def test_stream_fold_parity_random_with_forced_widen(monkeypatch):
    """Property test: random streams x random K, with the table forced
    to start at a tiny capacity rung (DSI_DEVICE_TABLE_CAP) so the vocab
    crosses it mid-stream — every run must widen at least once and still
    match the host-merge path bit-for-bit."""
    monkeypatch.setenv("DSI_DEVICE_TABLE_CAP", "32")
    mesh = _mesh()
    widens = 0
    for seed in (7, 23):
        rng = np.random.default_rng(seed)
        k = int(rng.integers(1, 7))
        blocks = [(" ".join(VOCAB[j] for j in rng.integers(0, 500, 300))
                   + "\n").encode()
                  for _ in range(int(rng.integers(6, 12)))]
        text = b"".join(blocks)
        want = dict(collections.Counter(WORDS.findall(text.decode())))
        base = wordcount_streaming(list(blocks), mesh=mesh, n_reduce=10,
                                   chunk_bytes=1 << 11, u_cap=64, depth=1)
        st: dict = {}
        res = wordcount_streaming(
            list(blocks), mesh=mesh, n_reduce=10, chunk_bytes=1 << 11,
            u_cap=64, depth=2, device_accumulate=True, sync_every=k,
            pipeline_stats=st)
        assert base is not None and res is not None
        assert _counts(res) == want
        assert res == base, (seed, k)
        widens += st["widens"]
        # Widen drains are extra pulls, but bounded by the acceptance
        # formula: pulls <= ceil(folds/K) + widens.
        assert st["sync_pulls"] <= math.ceil(st["folds"] / k)
        assert st["step_pulls"] == 0
    assert widens >= 1  # the tiny rung actually forced the widen path


def test_stream_replayed_step_folds_exact_output():
    """A mid-stream capacity overflow replays through the ladder; with
    device accumulation the REPLAYED (exact) output folds on device —
    still zero per-step pulls, still bit-identical to depth=1."""
    rng = np.random.default_rng(23)
    small = ["aa", "bb", "cc", "dd"]
    blocks = []
    for i in range(12):
        vocab = small if i < 6 else VOCAB[:700]
        picks = rng.integers(0, len(vocab), 400)
        blocks.append((" ".join(vocab[j] for j in picks) + "\n").encode())
    text = b"".join(blocks)
    want = dict(collections.Counter(WORDS.findall(text.decode())))
    mesh = _mesh()
    base = wordcount_streaming(list(blocks), mesh=mesh, n_reduce=10,
                               chunk_bytes=1 << 11, u_cap=64, depth=1)
    st: dict = {}
    res = wordcount_streaming(list(blocks), mesh=mesh, n_reduce=10,
                              chunk_bytes=1 << 11, u_cap=64, depth=3,
                              device_accumulate=True, sync_every=8,
                              pipeline_stats=st)
    assert base is not None and res is not None
    assert _counts(res) == want
    assert res == base
    assert st["replays"] >= 1   # the deferred check actually fired
    assert st["step_pulls"] == 0  # the replay folded, it did not pull


def test_wcstream_cli_device_accumulate_matches_oracle(tmp_path):
    """The service is reachable without importing internals: wcstream
    --device-accumulate end-to-end vs the sequential oracle."""
    from dsi_tpu.cli import wcstream
    from dsi_tpu.utils.corpus import ensure_corpus
    from tests.harness import merged_output, oracle_output

    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2,
                          file_size=20_000)
    want = oracle_output("wc", files, str(tmp_path))
    wd = tmp_path / "out"
    wd.mkdir()
    rc = wcstream.main(["--nreduce", "10", "--chunk-bytes", "4096",
                        "--check", "--device-accumulate", "--sync-every",
                        "4", "--stats", "--workdir", str(wd)] + files)
    assert rc == 0  # --check exits 2 on a parity failure
    assert merged_output(str(wd)) == want


def test_stream_device_accumulate_aot_warm_covers_everything(tmp_path,
                                                             monkeypatch):
    """The bench/chip configuration: aot=True + device_accumulate on a
    single-device mesh.  warm_stream_aot(device_accumulate=True) must
    pre-compile every program the stream then executes — step, pack,
    fold, clear, table pack — so the chip run is loads, never compiles;
    and the result must still match the Counter oracle."""
    from dsi_tpu.backends import aotcache
    from dsi_tpu.parallel.streaming import warm_stream_aot

    monkeypatch.setenv("DSI_AOT_CACHE_DIR", str(tmp_path / "aot"))
    mesh = default_mesh(1)
    warm_stream_aot(mesh=mesh, chunk_bytes=1 << 14, caps=(1 << 10,),
                    device_accumulate=True)
    compiles_after_warm = aotcache.stats["compiles"]
    text = ("device resident accumulate " * 900).encode()
    st: dict = {}
    res = wordcount_streaming([text], mesh=mesh, n_reduce=10,
                              chunk_bytes=1 << 14, u_cap=1 << 10, aot=True,
                              device_accumulate=True, sync_every=8,
                              pipeline_stats=st)
    assert res is not None
    want = collections.Counter(WORDS.findall(text.decode()))
    assert _counts(res) == dict(want)
    assert st["folds"] >= 1 and st["step_pulls"] == 0
    assert aotcache.stats["compiles"] == compiles_after_warm


# ── TF-IDF wave walk integration ──────────────────────────────────────


def _tfidf_docs(n_docs: int, seed: int):
    rng = np.random.default_rng(seed)
    return [(" ".join(VOCAB[j] for j in
                      rng.integers(0, 200, int(rng.integers(30, 250))))
             + "\n").encode() for _ in range(n_docs)]


def test_tfidf_device_accumulate_matches_per_wave_pulls():
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    mesh = _mesh()
    docs = _tfidf_docs(20, seed=5)
    base = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9)
    st: dict = {}
    dev = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                        device_accumulate=True, sync_every=2,
                        wave_stats=st)
    assert base is not None and dev is not None
    assert dev == base  # same postings, same per-word order
    assert st["appends"] >= 1 and st["sync_pulls"] >= 1
    assert st["step_pulls"] == 0


def test_tfidf_device_accumulate_overflow_drains_early(monkeypatch):
    """A buffer trimmed below the window's postings overflows once a few
    waves accumulate: the append no-ops, the walk drains and retries,
    and nothing is lost or doubled."""
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    monkeypatch.setenv("DSI_DEVICE_POSTINGS_CAP", "512")
    mesh = _mesh()
    docs = _tfidf_docs(48, seed=9)
    base = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9)
    st: dict = {}
    # sync_every far beyond the wave count: only overflow can drain
    # before the end-of-walk sync.
    dev = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                        device_accumulate=True, sync_every=10_000,
                        wave_stats=st)
    assert base is not None and dev is not None
    assert dev == base
    assert st["append_overflows"] >= 1  # the early-sync path actually ran


def test_tfidf_device_accumulate_partition_slice():
    from dsi_tpu.parallel.tfidf import tfidf_sharded

    mesh = _mesh()
    docs = _tfidf_docs(12, seed=3)
    base = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9)
    sl = tfidf_sharded(docs, mesh=mesh, n_reduce=10, u_cap=1 << 9,
                       partitions={0, 1, 2}, device_accumulate=True,
                       sync_every=3)
    assert base is not None and sl is not None
    assert sl == {w: v for w, v in base.items() if v[0] in (0, 1, 2)}
