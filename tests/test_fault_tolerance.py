"""Fault-tolerance tests: the 10 s requeue path under real worker death.

SURVEY.md §4 flags that the reference never tests its own fault-tolerance
mechanism (coordinator.go:70-77,99-106).  These tests kill real worker
processes mid-job and assert the job still completes with oracle parity —
safety coming from atomic temp-file-rename commits (worker.go:91,148) and
reduce tolerating missing intermediates (worker.go:106-108).
"""

import os
import subprocess
import sys
import time

import pytest

from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args, cwd, env):
    return subprocess.Popen([sys.executable, "-m", *args], cwd=cwd, env=env,
                            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_crash_app_parity(tmp_path):
    """1 coordinator + 4 workers running the crash app (random os._exit and
    stalls); dead workers are replaced; output must equal the nocrash oracle."""
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=6, file_size=30_000)
    want = oracle_output("nocrash", files, str(tmp_path))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSI_MR_SOCKET"] = str(tmp_path / "mr.sock")
    env["DSI_CRASH_EXIT_PROB"] = "0.3"
    env["DSI_CRASH_STALL_PROB"] = "0.15"
    env["DSI_CRASH_STALL_S"] = "2.5"
    wd = str(tmp_path)

    coord = _spawn(["dsi_tpu.cli.mrcoordinator", "--task-timeout", "2.0",
                    *files], wd, env)
    try:
        time.sleep(0.5)  # socket-creation grace (test-mr.sh:39-40)
        workers = []
        deadline = time.time() + 120
        while coord.poll() is None:
            if time.time() > deadline:
                pytest.fail("crash job did not finish in 120s")
            # keep ~4 live workers, replacing any that crashed
            workers = [w for w in workers if w.poll() is None]
            while len(workers) < 4:
                workers.append(_spawn(["dsi_tpu.cli.mrworker", "crash"], wd, env))
            time.sleep(0.3)
        for w in workers:
            w.wait(timeout=30)
    finally:
        if coord.poll() is None:
            coord.kill()
    assert merged_output(wd) == want


@pytest.mark.slow
def test_worker_killed_externally(tmp_path):
    """SIGKILL a healthy worker mid-map; the requeue must recover."""
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=4, file_size=50_000)
    want = oracle_output("wc", files, str(tmp_path))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSI_MR_SOCKET"] = str(tmp_path / "mr.sock")
    wd = str(tmp_path)

    coord = _spawn(["dsi_tpu.cli.mrcoordinator", "--task-timeout", "2.0",
                    *files], wd, env)
    try:
        time.sleep(0.5)
        victim = _spawn(["dsi_tpu.cli.mrworker", "wc"], wd, env)
        time.sleep(0.3)
        victim.kill()  # dies holding an in-progress task
        survivor = _spawn(["dsi_tpu.cli.mrworker", "wc"], wd, env)
        coord.wait(timeout=90)
        survivor.wait(timeout=30)
    finally:
        for p in (coord,):
            if p.poll() is None:
                p.kill()
    assert merged_output(wd) == want
