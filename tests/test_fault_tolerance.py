"""Fault-tolerance tests: the 10 s requeue path under real worker death.

SURVEY.md §4 flags that the reference never tests its own fault-tolerance
mechanism (coordinator.go:70-77,99-106).  These tests kill real worker
processes mid-job and assert the job still completes with oracle parity —
safety coming from atomic temp-file-rename commits (worker.go:91,148) and
reduce tolerating missing intermediates (worker.go:106-108).
"""

import os
import subprocess
import sys
import time

import pytest

from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args, cwd, env):
    return subprocess.Popen([sys.executable, "-m", *args], cwd=cwd, env=env,
                            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_crash_app_parity(tmp_path):
    """1 coordinator + 4 workers running the crash app (random os._exit and
    stalls); dead workers are replaced; output must equal the nocrash oracle."""
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=6, file_size=30_000)
    want = oracle_output("nocrash", files, str(tmp_path))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSI_MR_SOCKET"] = str(tmp_path / "mr.sock")
    env["DSI_CRASH_EXIT_PROB"] = "0.3"
    env["DSI_CRASH_STALL_PROB"] = "0.15"
    env["DSI_CRASH_STALL_S"] = "2.5"
    wd = str(tmp_path)

    coord = _spawn(["dsi_tpu.cli.mrcoordinator", "--task-timeout", "2.0",
                    *files], wd, env)
    try:
        time.sleep(0.5)  # socket-creation grace (test-mr.sh:39-40)
        workers = []
        deadline = time.time() + 120
        while coord.poll() is None:
            if time.time() > deadline:
                pytest.fail("crash job did not finish in 120s")
            # keep ~4 live workers, replacing any that crashed
            workers = [w for w in workers if w.poll() is None]
            while len(workers) < 4:
                workers.append(_spawn(["dsi_tpu.cli.mrworker", "crash"], wd, env))
            time.sleep(0.3)
        for w in workers:
            w.wait(timeout=30)
    finally:
        if coord.poll() is None:
            coord.kill()
    assert merged_output(wd) == want


@pytest.mark.slow
def test_worker_killed_externally(tmp_path):
    """SIGKILL a healthy worker mid-map; the requeue must recover."""
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=4, file_size=50_000)
    want = oracle_output("wc", files, str(tmp_path))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSI_MR_SOCKET"] = str(tmp_path / "mr.sock")
    wd = str(tmp_path)

    coord = _spawn(["dsi_tpu.cli.mrcoordinator", "--task-timeout", "2.0",
                    *files], wd, env)
    try:
        time.sleep(0.5)
        victim = _spawn(["dsi_tpu.cli.mrworker", "wc"], wd, env)
        time.sleep(0.3)
        victim.kill()  # dies holding an in-progress task
        survivor = _spawn(["dsi_tpu.cli.mrworker", "wc"], wd, env)
        coord.wait(timeout=90)
        survivor.wait(timeout=30)
    finally:
        for p in (coord,):
            if p.poll() is None:
                p.kill()
    assert merged_output(wd) == want


def test_duplicate_reduce_after_gc_keeps_full_output(tmp_path):
    """The reference's latent duplicate-reduce race (worker.go:148,151-154),
    reproduced deterministically: reducer A commits mr-out-r and GCs the
    intermediates; a re-queued duplicate B then reads the (now missing,
    tolerated — worker.go:106-108) intermediates and commits an EMPTY
    partition.  With last-writer-wins (the reference) B's rename clobbers
    A's full output — whole partitions vanish, which is exactly what the
    tiny-timeout race soak caught.  Our first-writer-wins commit
    (utils/atomicio.py) must keep A's file."""
    from dsi_tpu.apps.wc import Reduce
    from dsi_tpu.mr.worker import (KeyValue, run_reduce_task,
                                   write_intermediates)

    wd = str(tmp_path)
    kva = [KeyValue(w, "1") for w in ["alpha", "beta", "gamma", "alpha"]]
    write_intermediates(kva, map_task=0, n_reduce=1, workdir=wd)

    run_reduce_task(Reduce, 0, n_map=1, workdir=wd)   # A: full commit + GC
    with open(os.path.join(wd, "mr-out-0")) as f:
        full = f.read()
    assert "alpha 2" in full

    run_reduce_task(Reduce, 0, n_map=1, workdir=wd)   # B: reads nothing
    with open(os.path.join(wd, "mr-out-0")) as f:
        assert f.read() == full, "duplicate reduce clobbered the output"


def test_fresh_job_overwrites_stale_outputs(tmp_path):
    """First-writer-wins must not leak ACROSS jobs: a rerun in the same cwd
    overwrites previous outputs (reference rerun behavior) because the
    coordinator clears stale mr-out-* for every task it will run."""
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator

    wd = str(tmp_path)
    stale = os.path.join(wd, "mr-out-0")
    with open(stale, "w") as f:
        f.write("stale 1\n")
    inp = os.path.join(wd, "in.txt")
    with open(inp, "w") as f:
        f.write("fresh words here\n")
    c = Coordinator([inp], 2, JobConfig(n_reduce=2, workdir=wd))
    try:
        assert not os.path.exists(stale)
    finally:
        c.close()


def test_journal_resume_preserves_unjournaled_output(tmp_path):
    """Resume must NOT clear stale mr-out-*: a reduce that committed its
    output and GC'd its intermediates right before a coordinator crash —
    but whose completion RPC never got journaled — leaves mr-out-<r> as the
    only copy of that partition.  The resumed job re-runs the task; its
    empty re-commit loses to the surviving file (first-writer-wins)."""
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator

    wd = str(tmp_path)
    inp = os.path.join(wd, "in.txt")
    with open(inp, "w") as f:
        f.write("words\n")
    jpath = os.path.join(wd, "journal")
    cfg = JobConfig(n_reduce=2, workdir=wd, journal_path=jpath)

    c1 = Coordinator([inp], 2, cfg)   # pre-crash incarnation
    c1.map_complete({"TaskNumber": 0})
    c1.close()
    # The unjournaled-but-committed partition (its intermediates GC'd):
    survivor = os.path.join(wd, "mr-out-1")
    with open(survivor, "w") as f:
        f.write("words 1\n")

    c2 = Coordinator([inp], 2, cfg)   # resume
    try:
        assert os.path.exists(survivor), "resume deleted the only copy"
        assert c2.c_map == 1 and c2.c_reduce == 0
    finally:
        c2.close()
