"""Differential end-to-end tests: distributed output == sequential oracle.

The reference's only test is exactly this check, as a bash script
(``main/test-mr.sh``): oracle via mrsequential, 1 coordinator + 3 workers,
``sort mr-out* | grep .`` vs the oracle's sorted output, byte-compared
(test-mr.sh:30-53).  Here it runs for wc, grep, and indexer, in-process.
"""

import pytest

from dsi_tpu.utils.corpus import ensure_corpus
from tests.harness import merged_output, oracle_output, run_distributed_threads


@pytest.fixture()
def corpus(tmp_path):
    return ensure_corpus(str(tmp_path / "inputs"), n_files=5, file_size=60_000)


def test_wc_parity(tmp_path, corpus):
    want = oracle_output("wc", corpus, str(tmp_path))
    run_distributed_threads("wc", corpus, str(tmp_path))
    assert merged_output(str(tmp_path)) == want
    assert len(want) > 1000  # corpus produced a real vocabulary


def test_indexer_parity(tmp_path, corpus):
    want = oracle_output("indexer", corpus, str(tmp_path))
    run_distributed_threads("indexer", corpus, str(tmp_path))
    assert merged_output(str(tmp_path)) == want


def test_grep_parity(tmp_path, corpus, monkeypatch):
    monkeypatch.setenv("DSI_GREP_PATTERN", r"[Tt]h")
    want = oracle_output("grep", corpus, str(tmp_path))
    assert want  # pattern must actually match something
    run_distributed_threads("grep", corpus, str(tmp_path))
    assert merged_output(str(tmp_path)) == want


def test_tfidf_parity(tmp_path, corpus, monkeypatch):
    # N (total docs) is job-level config a per-key reduce cannot derive
    # (apps/tfidf.py n_docs_from_env) — the harness exports it the same way.
    monkeypatch.setenv("DSI_TFIDF_NDOCS", str(len(corpus)))
    want = oracle_output("tfidf", corpus, str(tmp_path))
    run_distributed_threads("tfidf", corpus, str(tmp_path))
    assert merged_output(str(tmp_path)) == want
    assert any(" " in l and ":" in l for l in want)  # df + doc:score rows


def test_single_worker_parity(tmp_path, corpus):
    # degenerate parallelism still correct
    want = oracle_output("wc", corpus, str(tmp_path))
    run_distributed_threads("wc", corpus, str(tmp_path), n_workers=1)
    assert merged_output(str(tmp_path)) == want


def test_more_workers_than_tasks(tmp_path):
    files = ensure_corpus(str(tmp_path / "inputs"), n_files=2, file_size=10_000)
    want = oracle_output("wc", files, str(tmp_path))
    run_distributed_threads("wc", files, str(tmp_path), n_workers=8, n_reduce=3)
    assert merged_output(str(tmp_path)) == want
